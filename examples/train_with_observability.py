"""End-to-end demo: train a Llama-style model on TPU with deepflow-tpu
attached (BASELINE config 3 in miniature).

    # terminal 1
    python -m deepflow_tpu.server.server

    # terminal 2 — zero-code:
    python -m deepflow_tpu.cli.runner --service llama-train \
        examples/train_with_observability.py
    #   ...or run directly (this file attaches itself when asked):
    python examples/train_with_observability.py --attach

    # then
    python -m deepflow_tpu.cli.dfctl tpu-flame
    python -m deepflow_tpu.cli.dfctl flame --service llama-train
    python -m deepflow_tpu.cli.dfctl query \
        "SELECT hlo_op, Sum(duration_ns) AS d, Sum(flops) AS f \
         FROM tpu_hlo_span GROUP BY hlo_op ORDER BY d DESC LIMIT 10" \
        --db profile
"""

import argparse
import time

import jax


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--attach", action="store_true",
                        help="attach the in-process agent directly")
    parser.add_argument("--server", default="127.0.0.1:20033")
    parser.add_argument("--steps", type=int, default=60)
    parser.add_argument("--d-model", type=int, default=512)
    parser.add_argument("--layers", type=int, default=6)
    parser.add_argument("--seq", type=int, default=512)
    parser.add_argument("--batch", type=int, default=8)
    args = parser.parse_args()

    if args.attach:
        from deepflow_tpu.agent.agent import attach
        from deepflow_tpu.agent.config import TpuProbeConfig
        attach(app_service="llama-train", servers=[args.server],
               tpuprobe=TpuProbeConfig(enabled=True, source="xplane",
                                       trace_interval_s=5.0,
                                       trace_duration_ms=1000))

    from deepflow_tpu.models.llama import (
        LlamaConfig, init_params, make_train_step)

    cfg = LlamaConfig(
        vocab=8192, d_model=args.d_model, n_layers=args.layers,
        n_heads=8, n_kv_heads=4, d_ff=int(args.d_model * 2.75),
        max_seq=args.seq * 2)
    params = init_params(cfg, jax.random.key(0))
    train_step, init_opt = make_train_step(cfg)
    opt_state = init_opt(params)
    step = jax.jit(train_step, donate_argnums=(0, 1))
    tokens = jax.random.randint(
        jax.random.key(1), (args.batch, args.seq), 0, cfg.vocab)

    print(f"training: d={args.d_model} L={args.layers} seq={args.seq} "
          f"batch={args.batch} on {jax.devices()[0].device_kind}")
    t0 = time.time()
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, tokens)
        if i % 10 == 0:
            print(f"step {i:4d} loss {float(jax.device_get(loss)):.4f}")
    loss = float(jax.device_get(loss))
    dt = time.time() - t0
    print(f"done: {args.steps} steps in {dt:.1f}s "
          f"({args.steps / dt:.2f} steps/s), final loss {loss:.4f}")


if __name__ == "__main__":
    main()
