PYTHON ?= python
JAX_ENV := env JAX_PLATFORMS=cpu

.PHONY: test selfmon-check cluster-check steps-check chaos-check ha-check \
	query-check ingest-check storage-check compaction-check readtier-check \
	trace-check overload-check live-check scrub-check bench native

test:
	timeout -k 10 870 $(JAX_ENV) $(PYTHON) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider -p no:xdist \
		-p no:randomly

# Brief e2e run of the real agent+server pipeline; exits non-zero if any
# hop's frame ledger fails to balance or any stage reports no heartbeat.
selfmon-check:
	timeout -k 10 120 $(JAX_ENV) $(PYTHON) -m deepflow_tpu.cli.selfmon_check

# Brief e2e run of a 3-shard cluster + agent fleet; exits non-zero if the
# federated count diverges from the union of shard counts or any
# cluster.* fan-out hop's frame ledger fails to balance.
cluster-check:
	timeout -k 10 120 $(JAX_ENV) $(PYTHON) -m deepflow_tpu.cli.cluster_check

# Disaggregated read tier: 1 ingest shard + 4 stateless querier
# subprocesses over a shared object store; exits non-zero if any
# replica's answer differs from the ingest node's, the distributed
# partial-aggregate cache rescans a warm bucket or its ledgers don't
# conserve, read throughput fails to scale (multi-core hosts), or the
# ingest write p99 moves under the query storm.
readtier-check:
	timeout -k 10 300 $(JAX_ENV) $(PYTHON) -m deepflow_tpu.cli.readtier_check

# Kill-and-recover run of the durable transport under seeded fault
# injection (conn resets + partial writes + a mid-stream server
# restart); exits non-zero unless every high-priority frame lands in
# the store exactly once and all hop ledgers balance.
chaos-check:
	timeout -k 10 120 $(JAX_ENV) $(PYTHON) -m deepflow_tpu.cli.chaos_check

# Replicated-ingest failover run: 3 subprocess shards at R=2, a sender
# fleet shipping to consistent-hash ring owners, one shard SIGKILLed
# mid-stream; exits non-zero unless federated queries stay EXACT (no
# missing shards, count equals frames sent) with zero HIGH loss.
ha-check:
	timeout -k 10 300 $(JAX_ENV) $(PYTHON) -m deepflow_tpu.cli.ha_check

# Brief e2e run of the step-health pipeline: synthetic 4-device pod with
# one injected 2x-slow device; exits non-zero unless the regression
# detector fires once and names that device and its dominant HLO.
steps-check:
	timeout -k 10 120 $(JAX_ENV) $(PYTHON) -m deepflow_tpu.cli.steps_check

# Golden parity of the three query paths (legacy / numpy / native) on a
# seeded corpus, federated merge-equivalence vs a single node, and a
# warm/cold cache latency report; exits non-zero on any divergence.
query-check:
	timeout -k 10 120 $(JAX_ENV) $(PYTHON) -m deepflow_tpu.cli.query_check

# Live-observability gate: standing queries under a 1M-row window must
# refresh incrementally >=10x faster than from-scratch and byte-identical
# to it (and to the DF_STANDING=0 kill-switch), 3 concurrent subscribers
# each see every generation exactly once, a breached alert fires via push
# within 2s, a 3-shard federated delta recomputes only the changed shard,
# and the query.standing / exporter.<kind> hop ledgers conserve.
live-check:
	timeout -k 10 600 $(JAX_ENV) $(PYTHON) -m deepflow_tpu.cli.live_check

# Native ingest throughput gate: same L4 frames through the native
# columnar path and the DF_NO_NATIVE pb fallback; exits non-zero unless
# native sustains >= 2.5x the fallback's rows/s (relative gate — a slow
# CI host can't fail a fast code path) with zero drops on both arms.
ingest-check:
	timeout -k 10 300 $(JAX_ENV) $(PYTHON) -m deepflow_tpu.cli.ingest_check

# Durable-write SIGKILL gate for the tiered store: a subprocess server
# with --storage is killed mid-stream; exits non-zero unless every
# pre-kill ACKED frame survives the crash from on-disk segments and all
# frames land exactly once after a restart on the same data_dir, then
# a TTL sweep must evict the aged segments with every dropped row
# ledgered under segment_evict (drops observed, never silent).
storage-check:
	timeout -k 10 300 $(JAX_ENV) $(PYTHON) -m deepflow_tpu.cli.storage_check

# Segment-format-v2 compaction gate: 200 small format-v1 segments are
# compacted into sorted runs; exits non-zero unless answers stay
# byte-identical, selective needle scans get >= 3x faster with bloom
# indexes demonstrably pruning runs, no v1 segment survives, the
# query.scan hop ledger balances, and crash-injected compactions
# (killed after staging AND after the manifest commit) both recover
# exactly and converge to v2 on the next cycle.
compaction-check:
	timeout -k 10 600 $(JAX_ENV) $(PYTHON) -m deepflow_tpu.cli.compaction_check

# Dogfooded query tracing gate: a federated 3-shard query must stitch
# into exactly one trace readable through the system's own Tempo API
# (coordinator + every shard exec + prune decisions, shard spans
# parented under their own shard.call), with byte-identical results
# tracing on/off, EXPLAIN ANALYZE stage sums within 20% of e2e, and a
# conserved query.trace hop ledger on every node.
trace-check:
	timeout -k 10 120 $(JAX_ENV) $(PYTHON) -m deepflow_tpu.cli.trace_check

# Closed-loop QoS gate: 3 tenants offer 10x their bulk quota through
# the real server; exits non-zero on any HIGH-class loss, a tenant
# starved or outside 2x of its weighted share, unbounded ingest p99,
# an unattributed drop, an unbalanced hop ledger, or a pressure spike
# that fails to raise-then-decay the advertised level.
overload-check:
	timeout -k 10 300 $(JAX_ENV) $(PYTHON) -m deepflow_tpu.cli.overload_check

# Self-healing storage gate: a 3-shard federated cluster under
# sustained ingest takes bit-flips into sealed segments, a corrupted
# object-store blob, and ENOSPC into one shard's flush path; exits
# non-zero unless every corruption is detected by the checksum scrub,
# quarantined through the manifest, and repaired from the healthy
# copy (queries annotated degraded in the gap, byte-identical to the
# expected aggregates after), acks HOLD through the full disk with
# zero HIGH loss after recovery, and every hop ledger conserves.
scrub-check:
	timeout -k 10 600 $(JAX_ENV) $(PYTHON) -m deepflow_tpu.cli.scrub_check

bench:
	$(JAX_ENV) $(PYTHON) bench.py

# Build every native library, then fail loudly if the freshly-built
# libdfnative.so does not load at the ABI the python bindings expect —
# a stale .so must break the build here, not silently fall back at
# runtime.
native:
	$(MAKE) -C deepflow_tpu/native
	$(PYTHON) -m deepflow_tpu.native --verify-abi
