PYTHON ?= python
JAX_ENV := env JAX_PLATFORMS=cpu

.PHONY: test selfmon-check bench native

test:
	timeout -k 10 870 $(JAX_ENV) $(PYTHON) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider -p no:xdist \
		-p no:randomly

# Brief e2e run of the real agent+server pipeline; exits non-zero if any
# hop's frame ledger fails to balance or any stage reports no heartbeat.
selfmon-check:
	timeout -k 10 120 $(JAX_ENV) $(PYTHON) -m deepflow_tpu.cli.selfmon_check

bench:
	$(JAX_ENV) $(PYTHON) bench.py

native:
	$(MAKE) -C deepflow_tpu/native libdfmemhook.so
