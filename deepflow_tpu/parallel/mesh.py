"""Device mesh construction + param sharding helpers."""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("data", "fsdp", "tensor")


def factor_devices(n: int) -> tuple[int, int, int]:
    """Factor n devices into (data, fsdp, tensor) mesh dims.

    Heuristic: tensor gets up to 4 (ICI-local), fsdp absorbs the middle,
    data the rest — mirrors common v5e fsdp+tp layouts.
    """
    tensor = 1
    for t in (4, 2):
        if n % t == 0 and n >= t:
            tensor = t
            break
    rem = n // tensor
    fsdp = 1
    for f in (8, 4, 2):
        if rem % f == 0 and rem >= f:
            fsdp = f
            break
    data = rem // fsdp
    return (data, fsdp, tensor)


def make_mesh(devices=None, shape: tuple[int, int, int] | None = None) -> Mesh:
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if shape is None:
        shape = factor_devices(n)
    assert int(np.prod(shape)) == n, f"mesh {shape} != {n} devices"
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, AXES)


def shard_map(f, *, mesh: Mesh, in_specs, out_specs, check_vma: bool = False):
    """Version-portable jax shard_map.

    jax >= 0.6 exposes ``jax.shard_map`` with a ``check_vma`` kwarg; older
    releases only have ``jax.experimental.shard_map.shard_map`` where the
    same switch is spelled ``check_rep``.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def shard_params(params, specs, mesh: Mesh):
    """Place a param tree onto the mesh according to a PartitionSpec tree."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs,
        is_leaf=lambda x: isinstance(x, P) or not isinstance(x, dict))


def named_sharding_tree(specs, mesh: Mesh):
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))
