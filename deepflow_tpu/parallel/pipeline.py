"""Pipeline parallelism: GPipe-style microbatch schedule over a 'pp' axis.

Stages hold contiguous layer slices (params sharded on the pp axis);
activations flow stage-to-stage with lax.ppermute while microbatches fill
the pipeline. The observability angle: each hop is a ppermute the TPU probe
attributes as ICI traffic, exactly like the reference observes NCCL
pipelines (SURVEY.md §2.9).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from deepflow_tpu.parallel.mesh import shard_map


def _pipeline_local(stage_params, micro_in, *, axis_name: str, stage_fn,
                    n_micro: int):
    """Per-device body. stage_params: this stage's layer slice (leading
    layer dim). micro_in: (n_micro, mb, ...) full microbatched input
    (only stage 0 reads it). Returns (n_micro, mb, ...) outputs (valid on
    the LAST stage; other stages return zeros)."""
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    mb_shape = micro_in.shape[1:]
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    out_buf = jnp.zeros_like(micro_in)
    cur = jnp.zeros(mb_shape, dtype=micro_in.dtype)

    def body(t, carry):
        cur, out_buf = carry
        # stage 0 ingests microbatch t (when one remains)
        feed_idx = jnp.minimum(t, n_micro - 1)
        cur = jnp.where(jnp.logical_and(stage == 0, t < n_micro),
                        micro_in[feed_idx], cur)
        # every stage applies its layers to whatever it holds
        y = stage_fn(stage_params, cur)
        # last stage retires microbatch (t - (n_stages-1)) at this tick
        done_idx = t - (n_stages - 1)
        store = jnp.logical_and(stage == n_stages - 1,
                                jnp.logical_and(done_idx >= 0,
                                                done_idx < n_micro))
        idx = jnp.clip(done_idx, 0, n_micro - 1)
        out_buf = jnp.where(
            store, out_buf.at[idx].set(y), out_buf)
        # activations advance one stage
        cur = jax.lax.ppermute(y, axis_name, perm)
        return cur, out_buf

    total_ticks = n_micro + n_stages - 1
    _, out_buf = jax.lax.fori_loop(0, total_ticks, body, (cur, out_buf))
    # only the last stage holds real outputs (zeros elsewhere): psum makes
    # the result replicated so out_specs=P() is sound
    return jax.lax.psum(out_buf, axis_name)


def pipeline_forward(params, x, stage_fn, mesh: Mesh, axis: str = "pp",
                     n_micro: int = 4):
    """Run x through layers pipelined across mesh axis `axis`.

    params: pytree with leading layer dim divisible by the pp axis size;
    x: (batch, ...) with batch divisible by n_micro;
    stage_fn(stage_params, mb) applies one stage's layer slice.
    Returns (batch, ...) outputs.
    """
    n_stages = mesh.shape[axis]
    batch = x.shape[0]
    assert batch % n_micro == 0, "batch must divide into microbatches"
    n_layers = jax.tree.leaves(params)[0].shape[0]
    assert n_layers % n_stages == 0, (
        f"layer dim {n_layers} must divide by pp={n_stages}")
    micro = x.reshape(n_micro, batch // n_micro, *x.shape[1:])

    param_specs = jax.tree.map(lambda _: P(axis), params)
    fn = shard_map(
        partial(_pipeline_local, axis_name=axis, stage_fn=stage_fn,
                n_micro=n_micro),
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_vma=False)
    out = fn(params, micro)
    return out.reshape(batch, *x.shape[1:])
