"""Ring attention: sequence/context parallelism over an ICI ring.

Long-context first-class support: the sequence axis is sharded across
devices; K/V blocks rotate around the ring via ppermute while each device
accumulates its queries' attention with an online (flash-style) softmax.
Compute overlaps communication naturally under XLA's async collective
scheduling; memory per device is O(S/n * S/n) per block instead of O(S^2).

Reference repo has no analog (it observes collectives, it doesn't run them);
pattern follows the public ring-attention recipe (Liu et al. 2023) expressed
as shard_map + lax.ppermute.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from deepflow_tpu.parallel.mesh import shard_map


def _ring_attn_local(q, k, v, axis_name: str, causal: bool, scale: float):
    """Per-device body under shard_map.

    q: (B, Sq, H, hd) local query block; k/v: (B, Sk, KV, hd) local block
    with H == KV * n_rep (GQA). The UNREPEATED K/V blocks rotate the ring —
    ppermute ships KV-head-sized payloads; heads expand locally per step.
    """
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    B, Sq, H, hd = q.shape
    Sk, KV = k.shape[1], k.shape[2]
    n_rep = H // KV
    q32 = q.astype(jnp.float32)

    m0 = jnp.full((B, H, Sq), -jnp.inf, dtype=jnp.float32)
    l0 = jnp.zeros((B, H, Sq), dtype=jnp.float32)
    acc0 = jnp.zeros((B, Sq, H, hd), dtype=jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def loop_body(s, carry):
        m, l, acc, k_cur, v_cur = carry
        src = (my - s) % n  # ring position the current k/v block came from
        k_rep = (jnp.repeat(k_cur, n_rep, axis=2) if n_rep > 1 else k_cur)
        v_rep = (jnp.repeat(v_cur, n_rep, axis=2) if n_rep > 1 else v_cur)
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q32, k_rep.astype(jnp.float32)) * scale
        if causal:
            q_pos = my * Sq + jnp.arange(Sq)
            k_pos = src * Sk + jnp.arange(Sk)
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, -jnp.inf)
        blk_max = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m, blk_max)
        # fully-masked rows keep m == -inf; guard the exp shift
        shift = jnp.where(jnp.isinf(m_new), 0.0, m_new)
        p = jnp.where(jnp.isinf(scores), 0.0,
                      jnp.exp(scores - shift[..., None]))
        corr = jnp.where(jnp.isinf(m), 0.0, jnp.exp(m - shift))
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = (acc * corr.transpose(0, 2, 1)[..., None]
                   + jnp.einsum("bhqk,bkhd->bqhd", p,
                                v_rep.astype(jnp.float32)))
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return m_new, l_new, acc_new, k_nxt, v_nxt

    carry = (m0, l0, acc0, k, v)
    m, l, acc, _, _ = jax.lax.fori_loop(0, n, loop_body, carry)
    l = jnp.maximum(l, 1e-30)
    out = acc / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, axis: str = "data",
                   causal: bool = True) -> jax.Array:
    """Sequence-parallel attention.

    q: (B, S, H, hd), k/v: (B, S, KV, hd) with S sharded over mesh axis
    `axis` and H a multiple of KV (GQA) — unrepeated K/V rotate the ring,
    so ppermute payloads stay KV-head-sized.
    """
    scale = 1.0 / (q.shape[-1] ** 0.5)
    spec = P(None, axis, None, None)
    fn = shard_map(
        partial(_ring_attn_local, axis_name=axis, causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
