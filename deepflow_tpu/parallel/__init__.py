"""Mesh/sharding utilities and sequence-parallel primitives.

The observability pipeline itself is host-side; this package exists because
deepflow-tpu ships TPU-first reference workloads (models/) whose dp/fsdp/tp/sp
shardings the probes observe — and because the driver dry-runs our multi-chip
training path over a virtual mesh.
"""

from deepflow_tpu.parallel.mesh import (  # noqa: F401
    make_mesh, shard_params, factor_devices)
from deepflow_tpu.parallel.ring_attention import ring_attention  # noqa: F401
