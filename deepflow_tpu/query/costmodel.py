"""Learned kernel/plan cost model for the encoded query path.

Motivated by "A Learned Performance Model for TPUs" (PAPERS.md): instead
of hand-tuned size thresholds, keep a small online model of observed
cost per kernel and pick the cheapest prediction. Two consumers:

- query/engine.py: native hash-group vs numpy lexsort for GROUP BY
  (the native kernel is O(n) but pays ctypes marshalling; lexsort is
  O(n log n) with zero marshalling — the crossover is machine- and
  cardinality-dependent, so it is learned, not guessed).
- query/cache.py: cache admission — a query whose observed cold cost is
  below the admission floor is not worth an entry.
- query/engine.py again: serial vs morsel-parallel scan degree — the
  parallel kernel's fixed overhead term is seeded with the pool dispatch
  cost, so small queries keep choosing the serial plan without a
  hand-tuned row threshold.

Deliberately tiny: EWMA ns/row + a fixed per-call overhead term per
kernel, with periodic exploration so a kernel whose relative cost
changed (different data shapes) gets re-measured.
"""

from __future__ import annotations

import threading

_EWMA = 0.3          # weight of the newest observation
_EXPLORE_EVERY = 64  # re-measure the non-preferred kernel this often


class KernelCostModel:
    """Pick the cheapest kernel by predicted cost = coef*n + overhead."""

    def __init__(self, kernels: tuple[str, ...] = ("native", "numpy"),
                 overhead_ns: dict[str, float] | None = None) -> None:
        self.kernels = kernels
        self._lock = threading.Lock()
        self.coef: dict[str, float | None] = {k: None for k in kernels}
        self.overhead = dict(overhead_ns or {})  # fixed ns per call
        self.calls = 0
        self._last_used = {k: 0 for k in kernels}

    def predict(self, kernel: str, n: int) -> float | None:
        c = self.coef.get(kernel)
        if c is None:
            return None
        return c * max(n, 1) + self.overhead.get(kernel, 0.0)

    def choose(self, n: int) -> str:
        with self._lock:
            self.calls += 1
            # measure any still-unmeasured kernel first
            for k in self.kernels:
                if self.coef[k] is None:
                    return k
            # periodic exploration: the kernel least recently used gets a
            # fresh measurement so a stale coefficient can't pin the choice
            stale = min(self.kernels, key=lambda k: self._last_used[k])
            if self.calls - self._last_used[stale] >= _EXPLORE_EVERY:
                return stale
            return min(self.kernels,
                       key=lambda k: self.predict(k, n) or float("inf"))

    def observe(self, kernel: str, n: int, ns: float) -> None:
        per_row = float(ns) / max(n, 1)
        with self._lock:
            if kernel not in self._last_used:
                return
            self._last_used[kernel] = self.calls
            c = self.coef.get(kernel)
            self.coef[kernel] = (per_row if c is None
                                 else c * (1 - _EWMA) + per_row * _EWMA)

    def snapshot(self) -> dict:
        with self._lock:
            return {"calls": self.calls,
                    "ns_per_row": {k: (round(v, 2) if v is not None
                                       else None)
                                   for k, v in self.coef.items()},
                    "overhead_ns": {k: round(v, 1)
                                    for k, v in self.overhead.items()}}
