"""Query layer: DF-SQL dialect over the embedded columnar store.

Reference analog: server/querier/engine/clickhouse (SQL dialect -> ClickHouse
SQL). Here the dialect compiles to vectorized numpy execution over
ColumnarTables, with SmartEncoding dictionary translation at the edges.
"""

from deepflow_tpu.query.sql import parse
from deepflow_tpu.query.engine import execute, QueryResult
from deepflow_tpu.query.flamegraph import build_flame_tree

__all__ = ["parse", "execute", "QueryResult", "build_flame_tree"]
