"""Tracing adapter: federate spans from EXTERNAL tracing backends into the
trace view.

Reference analog: server/querier/app/tracing-adapter (pluggable adapters —
SkyWalking et al — that fetch a trace from a third-party APM by trace id
and splice its spans into DeepFlow's tree, so app-instrumented spans and
network/eBPF spans render as ONE trace). Embedded redesign: adapters are
HTTP fetchers for the two open formats that cover the ecosystem —
Jaeger's query API and Tempo/OTLP JSON — merged into the same TraceSpan
tree build_trace produces from flow logs.
"""

from __future__ import annotations

import json
import logging
import urllib.parse
import urllib.request

from deepflow_tpu.query.tracing import TraceSpan

log = logging.getLogger("df.tracing-adapter")


def _get_json(url: str, timeout: float = 5.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.load(r)


class JaegerAdapter:
    """GET {base}/api/traces/{trace_id} (Jaeger query service JSON)."""

    name = "jaeger"

    def __init__(self, base_url: str) -> None:
        self.base = base_url.rstrip("/")

    def fetch(self, trace_id: str) -> list[TraceSpan]:
        data = _get_json(
            f"{self.base}/api/traces/{urllib.parse.quote(trace_id)}")
        out: list[TraceSpan] = []
        for trace in data.get("data", []):
            procs = {pid: p.get("serviceName", "")
                     for pid, p in (trace.get("processes") or {}).items()}
            for sp in trace.get("spans", []):
                parent = ""
                for ref in sp.get("references", []):
                    if ref.get("refType") == "CHILD_OF":
                        parent = ref.get("spanID", "")
                start_us = int(sp.get("startTime", 0))
                dur_us = int(sp.get("duration", 0))
                out.append(TraceSpan(
                    span_id=sp.get("spanID", ""),
                    parent_span_id=parent,
                    name=sp.get("operationName", ""),
                    service=procs.get(sp.get("processID", ""), ""),
                    l7_protocol="app",
                    start_ns=start_us * 1000,
                    end_ns=(start_us + dur_us) * 1000,
                    status="ok",
                    response_code=0,
                    kind="external",
                    attrs={"adapter": self.name}))
        return out


class OtlpJsonAdapter:
    """GET {base}/api/traces/{trace_id} returning OTLP-JSON resourceSpans
    (Tempo-style)."""

    name = "otlp"

    def __init__(self, base_url: str) -> None:
        self.base = base_url.rstrip("/")

    def fetch(self, trace_id: str) -> list[TraceSpan]:
        data = _get_json(
            f"{self.base}/api/traces/{urllib.parse.quote(trace_id)}")
        out: list[TraceSpan] = []
        batches = data.get("resourceSpans", []) or \
            data.get("batches", [])
        for rs in batches:
            service = ""
            for attr in (rs.get("resource") or {}).get("attributes", []):
                if attr.get("key") == "service.name":
                    service = str(
                        (attr.get("value") or {}).get("stringValue", ""))
            for ss in rs.get("scopeSpans",
                             rs.get("instrumentationLibrarySpans", [])):
                for sp in ss.get("spans", []):
                    start = int(sp.get("startTimeUnixNano", 0))
                    end = int(sp.get("endTimeUnixNano", start))
                    out.append(TraceSpan(
                        span_id=sp.get("spanId", ""),
                        parent_span_id=sp.get("parentSpanId", ""),
                        name=sp.get("name", ""),
                        service=service,
                        l7_protocol="app",
                        start_ns=start,
                        end_ns=end,
                        status="ok",
                        response_code=0,
                        kind="external",
                        attrs={"adapter": self.name}))
        return out


_ADAPTERS = {"jaeger": JaegerAdapter, "otlp": OtlpJsonAdapter}


class AdapterRegistry:
    """Configured external backends, merged into build_trace output."""

    def __init__(self) -> None:
        self._adapters: list = []

    def add(self, kind: str, base_url: str) -> None:
        cls = _ADAPTERS.get(kind)
        if cls is None:
            raise ValueError(
                f"unknown adapter {kind!r}; known: {sorted(_ADAPTERS)}")
        base_url = base_url.rstrip("/")
        if not base_url.startswith(("http://", "https://")):
            raise ValueError(
                f"base_url must be an http(s) URL, got {base_url!r}")
        for a in self._adapters:  # idempotent: reconcile loops re-POST
            if a.name == kind and a.base == base_url:
                return
        self._adapters.append(cls(base_url))

    def remove(self, base_url: str) -> bool:
        base_url = base_url.rstrip("/")
        before = len(self._adapters)
        self._adapters = [a for a in self._adapters
                          if a.base != base_url]
        return len(self._adapters) != before

    def list(self) -> list[dict]:
        return [{"kind": a.name, "base_url": a.base}
                for a in self._adapters]

    def merge_into(self, tree: dict, trace_id: str) -> dict:
        """Fetch external spans and splice them into a build_trace tree
        (parent links by span id when the app propagated W3C context,
        time containment otherwise)."""
        external: list[TraceSpan] = []
        if not self._adapters:
            return tree
        # concurrent fetches: one dead backend must not serialize a 5s
        # stall per adapter into every trace query
        import concurrent.futures as _fut
        import time as _time
        with _fut.ThreadPoolExecutor(
                max_workers=min(4, len(self._adapters))) as pool:
            futs = {pool.submit(a.fetch, trace_id): a
                    for a in self._adapters}
            for f in _fut.as_completed(futs):
                a = futs[f]
                try:
                    external.extend(f.result())
                except Exception as e:
                    # visible, but throttled to one warning/min per adapter
                    now = _time.monotonic()
                    last = getattr(a, "_last_warn", 0)
                    if now - last > 60:
                        a._last_warn = now
                        log.warning("tracing adapter %s (%s) failed: %s",
                                    a.name, a.base, e)
                    else:
                        log.debug("adapter %s fetch failed: %s", a.name, e)
        if not external:
            return tree

        def index(node: dict, acc: dict) -> None:
            acc[node["span_id"]] = node
            for c in node.get("children", []):
                index(c, acc)

        by_id: dict = {}
        for root in tree.get("spans", []):
            index(root, by_id)
        ext_by_id = {s.span_id: s.to_dict() for s in external}
        placed = set()
        # parent-by-id, TOPOLOGICALLY: only attach to a parent already in
        # the tree (flow span or previously-placed external) — mutually-
        # referencing externals can't form a cycle this way; they fall
        # through to containment/root placement instead
        progress = True
        while progress:
            progress = False
            for s in external:
                if s.span_id in placed:
                    continue
                d = ext_by_id[s.span_id]
                parent = by_id.get(s.parent_span_id)
                if parent is None and s.parent_span_id in placed:
                    parent = ext_by_id.get(s.parent_span_id)
                if parent is not None and parent is not d:
                    parent.setdefault("children", []).append(d)
                    placed.add(s.span_id)
                    progress = True
        for s in external:
            if s.span_id in placed:
                continue
            best = None
            for node in by_id.values():
                if node["start_ns"] <= s.start_ns and \
                        s.end_ns <= node["end_ns"]:
                    if best is None or (node["end_ns"] - node["start_ns"]
                                        ) < (best["end_ns"]
                                             - best["start_ns"]):
                        best = node
            if best is not None:
                best.setdefault("children", []).append(ext_by_id[s.span_id])
            else:
                tree.setdefault("spans", []).append(ext_by_id[s.span_id])
            placed.add(s.span_id)
        tree["span_count"] = tree.get("span_count", 0) + len(external)
        tree["external_spans"] = len(external)
        return tree
