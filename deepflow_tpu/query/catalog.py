"""Tag/metric catalog + derived-metric registry.

Reference analog: server/querier/db_descriptions/ (the per-table tag and
metric catalogs that drive `show tags/metrics` and Grafana autocomplete)
plus the derived-metric registry inside
server/querier/engine/clickhouse/metrics/ (rtt = rtt_sum/rtt_count etc.).
Here both are generated from the live schema instead of static text files,
so they can never drift from the store.
"""

from __future__ import annotations

from deepflow_tpu.query import sql as S
from deepflow_tpu.store import schema

# Columns that are dimensions even though numeric
_NUMERIC_TAGS = {
    "agent_id", "host_id", "tpu_worker", "slice_id", "pid", "tid",
    "server_port", "port_src", "port_dst", "direction", "flow_id",
    "gprocess_id_0", "gprocess_id_1", "request_id", "tap_port",
    "tunnel_id", "device_id", "chip_id", "core_id", "program_id",
    "run_id", "step", "metric_id", "label_set_id", "time", "start_time",
    "end_time", "end_ns", "straggler_device",
}

# metric name -> per-aggregate rewrite, per table family (longest prefix
# wins). Shapes:
#   ("ratio", num, den): Avg(m) = Sum(num)/Sum(den)
#   ("col", c):          Agg(m) = Agg(c)
#   ("sum2", a, b):      Sum(m) = Sum(a)+Sum(b)
DERIVED: dict[str, dict[str, dict[str, tuple]]] = {
    "flow_metrics.network": {
        "rtt": {"AVG": ("ratio", "rtt_sum", "rtt_count")},
    },
    "flow_metrics.application": {
        "rrt": {"AVG": ("ratio", "rrt_sum", "rrt_count"),
                "MAX": ("col", "rrt_max")},
        "error": {"SUM": ("sum2", "error_client", "error_server")},
    },
}


def derived_for(table_name: str) -> dict:
    best = {}
    for prefix, metrics in DERIVED.items():
        if table_name.startswith(prefix):
            best = metrics
    return best


def rewrite_derived(expr, table_name: str, columns: set):
    """AST rewrite: Agg(derived_metric) -> its definition over the real
    columns. Only rewrites names that are NOT real columns of the table,
    so raw tables (flow_log.l4_flow_log has a real `rtt`) are untouched."""
    metrics = derived_for(table_name)
    if not metrics:
        return expr

    def walk(e):
        if isinstance(e, S.Func):
            if (e.name in S.AGG_FUNCS and e.args
                    and isinstance(e.args[0], S.Col)
                    and e.args[0].name not in columns
                    and e.args[0].name in metrics):
                rules = metrics[e.args[0].name]
                rule = rules.get(e.name)
                if rule is None:
                    raise _DerivedError(
                        f"{e.name} is not defined for derived metric "
                        f"{e.args[0].name!r} (supported: "
                        f"{', '.join(sorted(rules))})")
                if rule[0] == "ratio":
                    return S.BinOp("/", S.Func("SUM", (S.Col(rule[1]),)),
                                   S.Func("SUM", (S.Col(rule[2]),)))
                if rule[0] == "col":
                    return S.Func(e.name, (S.Col(rule[1]),))
                if rule[0] == "sum2":
                    return S.BinOp("+", S.Func("SUM", (S.Col(rule[1]),)),
                                   S.Func("SUM", (S.Col(rule[2]),)))
            return S.Func(e.name, tuple(walk(a) for a in e.args),
                          distinct=e.distinct)
        if isinstance(e, S.BinOp):
            right = (e.right if isinstance(e.right, tuple)
                     else walk(e.right))
            return S.BinOp(e.op, walk(e.left), right)
        if isinstance(e, S.Not):
            return S.Not(walk(e.expr))
        if isinstance(e, S.Case):
            return S.Case(
                tuple((walk(c), walk(v)) for c, v in e.whens),
                walk(e.default) if e.default is not None else None)
        return e

    return walk(expr)


class _DerivedError(Exception):
    pass


# -- show tags / metrics ----------------------------------------------------

def _split(cols: list) -> tuple[list, list]:
    tags, metrics = [], []
    for c in cols:
        if c.kind in ("str", "enum") or c.name in _NUMERIC_TAGS:
            tags.append(c)
        else:
            metrics.append(c)
    return tags, metrics


def _resolve(table_name: str) -> tuple[str, list]:
    if table_name in schema.TABLES:
        return table_name, schema.TABLES[table_name]
    for cand in (f"{table_name}.1s", f"flow_metrics.{table_name}.1s",
                 f"flow_log.{table_name}", f"profile.{table_name}",
                 f"event.{table_name}"):
        if cand in schema.TABLES:
            return cand, schema.TABLES[cand]
    raise KeyError(table_name)


def show(what: str, table: str | None = None) -> dict:
    """Execute a SHOW statement against the schema catalog. Returns the
    querier wire shape {columns, values}."""
    if what == "databases":
        dbs = sorted({t.split(".")[0] for t in schema.TABLES})
        return {"columns": ["name"], "values": [[d] for d in dbs]}
    if what == "tables":
        return {"columns": ["name"],
                "values": [[t] for t in sorted(schema.TABLES)]}
    name, cols = _resolve(table)
    tags, metrics = _split(cols)
    if what == "tags":
        values = []
        for c in tags:
            typ = ("enum" if c.kind == "enum"
                   else "string" if c.kind == "str" else "int")
            enum_vals = ",".join(c.enum_values) if c.kind == "enum" else ""
            values.append([c.name, typ, enum_vals])
        return {"columns": ["name", "type", "enum_values"],
                "values": values, "table": name}
    if what == "metrics":
        values = [[c.name, "counter", c.kind] for c in metrics]
        for m, rules in derived_for(name).items():
            if m not in {c.name for c in cols}:
                values.append(
                    [m, "derived(" + ",".join(sorted(rules)) + ")", "f64"])
        return {"columns": ["name", "category", "type"],
                "values": values, "table": name}
    raise KeyError(what)
