"""DF-SQL: tokenizer + recursive-descent parser.

Dialect (subset mirroring the reference querier's surface,
server/querier/engine/clickhouse/parse.go):

    SELECT expr [AS alias], ... FROM table
    [WHERE cond] [GROUP BY expr, ...] [HAVING cond]
    [ORDER BY expr [ASC|DESC], ...] [LIMIT n]

    SHOW DATABASES | SHOW TABLES | SHOW TAGS FROM t | SHOW METRICS FROM t

Aggregates: Sum, Avg, Min, Max, Count, Last, Percentile(x, p).
Scalars: time(time, interval_s) — time bucketing.
Conditions: = != <> < <= > >= IN (...) LIKE 'pat%' AND OR NOT ( ).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

KEYWORDS = {"SELECT", "FROM", "WHERE", "GROUP", "ORDER", "BY", "LIMIT",
            "AS", "AND", "OR", "NOT", "IN", "LIKE", "ASC", "DESC",
            "HAVING", "SHOW", "DISTINCT", "CASE", "WHEN", "THEN",
            "ELSE", "END"}
AGG_FUNCS = {"SUM", "AVG", "MIN", "MAX", "COUNT", "LAST", "PERCENTILE"}
SCALAR_FUNCS = {"TIME"}

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<num>\d+\.\d+|\d+)
  | (?P<str>'(?:[^'\\]|\\.)*')
  | (?P<ident>[A-Za-z_][A-Za-z0-9_.]*)
  | (?P<op><>|!=|<=|>=|=|<|>|\(|\)|,|\*|/|\+|-)
""", re.VERBOSE)


@dataclass(frozen=True)
class Token:
    kind: str  # num | str | ident | kw | op | eof
    value: str
    pos: int


class SqlError(Exception):
    pass


def tokenize(sql: str) -> list[Token]:
    out, pos = [], 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if not m:
            raise SqlError(f"bad token at {pos}: {sql[pos:pos+20]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        val = m.group()
        if kind == "ident" and val.upper() in KEYWORDS:
            out.append(Token("kw", val.upper(), m.start()))
        elif kind == "str":
            out.append(Token("str", val[1:-1].replace("\\'", "'"), m.start()))
        else:
            out.append(Token(kind, val, m.start()))
    out.append(Token("eof", "", len(sql)))
    return out


# -- AST --------------------------------------------------------------------

@dataclass(frozen=True)
class Col:
    name: str


@dataclass(frozen=True)
class Lit:
    value: object  # int | float | str


@dataclass(frozen=True)
class Case:
    """CASE WHEN cond THEN expr [WHEN ...] [ELSE expr] END."""
    whens: tuple     # ((cond, expr), ...)
    default: object = None


@dataclass(frozen=True)
class Func:
    name: str      # upper-cased
    args: tuple
    distinct: bool = False   # COUNT(DISTINCT col)


@dataclass(frozen=True)
class BinOp:
    op: str        # = != < <= > >= + - * / AND OR IN LIKE
    left: object
    right: object


@dataclass(frozen=True)
class Not:
    expr: object


@dataclass(frozen=True)
class Star:
    pass


@dataclass
class SelectItem:
    expr: object
    alias: str | None = None


@dataclass
class Select:
    items: list[SelectItem]
    table: str
    where: object | None = None
    group_by: list = field(default_factory=list)
    having: object | None = None
    order_by: list = field(default_factory=list)  # (expr, desc: bool)
    limit: int | None = None


@dataclass
class Show:
    """SHOW DATABASES | TABLES | TAGS FROM t | METRICS FROM t
    (reference: querier `show tags/metrics` introspection backed by
    db_descriptions/)."""
    what: str                 # databases | tables | tags | metrics
    table: str | None = None


@dataclass
class Explain:
    """EXPLAIN [ANALYZE] <select>.  Carries the parsed inner select AND
    its original text slice — federation and the query cache key on the
    SQL text, so the explain path must hand them the text the planner
    would have seen for a plain query."""
    select: Select
    analyze: bool = False
    sql: str = ""             # inner SELECT text, sliced from the input


class _Parser:
    def __init__(self, tokens: list[Token]):
        self.toks = tokens
        self.i = 0

    def peek(self) -> Token:
        return self.toks[self.i]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, kind: str, value: str | None = None) -> Token:
        t = self.next()
        if t.kind != kind or (value is not None and t.value != value):
            raise SqlError(f"expected {value or kind}, got {t.value!r} at {t.pos}")
        return t

    def accept_kw(self, *kws: str) -> Token | None:
        t = self.peek()
        if t.kind == "kw" and t.value in kws:
            return self.next()
        return None

    # select := SELECT items FROM ident [WHERE ...] ...
    def parse_select(self) -> Select:
        self.expect("kw", "SELECT")
        items = [self.parse_select_item()]
        while self.peek().kind == "op" and self.peek().value == ",":
            self.next()
            items.append(self.parse_select_item())
        self.expect("kw", "FROM")
        table = self.expect("ident").value
        sel = Select(items=items, table=table)
        if self.accept_kw("WHERE"):
            sel.where = self.parse_expr()
        if self.accept_kw("GROUP"):
            self.expect("kw", "BY")
            sel.group_by.append(self.parse_expr())
            while self.peek().value == ",":
                self.next()
                sel.group_by.append(self.parse_expr())
        if self.accept_kw("HAVING"):
            sel.having = self.parse_expr()
        if self.accept_kw("ORDER"):
            self.expect("kw", "BY")
            sel.order_by.append(self.parse_order_item())
            while self.peek().value == ",":
                self.next()
                sel.order_by.append(self.parse_order_item())
        if self.accept_kw("LIMIT"):
            sel.limit = int(self.expect("num").value)
        if self.peek().kind != "eof":
            t = self.peek()
            raise SqlError(f"trailing input at {t.pos}: {t.value!r}")
        return sel

    def parse_select_item(self) -> SelectItem:
        expr = self.parse_expr()
        alias = None
        if self.accept_kw("AS"):
            alias = self.expect("ident").value
        return SelectItem(expr, alias)

    def parse_order_item(self):
        expr = self.parse_expr()
        desc = False
        if self.accept_kw("DESC"):
            desc = True
        else:
            self.accept_kw("ASC")
        return (expr, desc)

    # precedence: OR < AND < NOT < cmp/IN/LIKE < add < mul < unary < primary
    def parse_expr(self):
        return self.parse_or()

    def parse_or(self):
        left = self.parse_and()
        while self.accept_kw("OR"):
            left = BinOp("OR", left, self.parse_and())
        return left

    def parse_and(self):
        left = self.parse_not()
        while self.accept_kw("AND"):
            left = BinOp("AND", left, self.parse_not())
        return left

    def parse_not(self):
        if self.accept_kw("NOT"):
            return Not(self.parse_not())
        return self.parse_cmp()

    def parse_cmp(self):
        left = self.parse_add()
        t = self.peek()
        if t.kind == "op" and t.value in ("=", "!=", "<>", "<", "<=", ">", ">="):
            self.next()
            op = "!=" if t.value == "<>" else t.value
            return BinOp(op, left, self.parse_add())
        if t.kind == "kw" and t.value in ("IN", "LIKE"):
            return self.parse_cmp_tail(left)
        if t.kind == "kw" and t.value == "NOT":
            # x NOT IN (...) / NOT LIKE
            save = self.i
            self.next()
            t2 = self.peek()
            if t2.kind == "kw" and t2.value in ("IN", "LIKE"):
                self.i = save
                self.next()  # NOT
                inner = self.parse_cmp_tail(left)
                return Not(inner)
            self.i = save
        return left

    def parse_cmp_tail(self, left):
        t = self.peek()
        if t.kind == "kw" and t.value == "IN":
            self.next()
            self.expect("op", "(")
            vals = [self.parse_literal()]
            while self.peek().value == ",":
                self.next()
                vals.append(self.parse_literal())
            self.expect("op", ")")
            return BinOp("IN", left, tuple(vals))
        if t.kind == "kw" and t.value == "LIKE":
            self.next()
            pat = self.expect("str").value
            return BinOp("LIKE", left, Lit(pat))
        raise SqlError(f"expected IN or LIKE at {t.pos}")

    def parse_literal(self) -> Lit:
        t = self.next()
        if t.kind == "num":
            return Lit(float(t.value) if "." in t.value else int(t.value))
        if t.kind == "str":
            return Lit(t.value)
        raise SqlError(f"expected literal at {t.pos}")

    def parse_add(self):
        left = self.parse_mul()
        while self.peek().kind == "op" and self.peek().value in ("+", "-"):
            op = self.next().value
            left = BinOp(op, left, self.parse_mul())
        return left

    def parse_mul(self):
        left = self.parse_unary()
        while self.peek().kind == "op" and self.peek().value in ("*", "/"):
            op = self.next().value
            left = BinOp(op, left, self.parse_unary())
        return left

    def parse_unary(self):
        t = self.peek()
        if t.kind == "op" and t.value == "-":
            self.next()
            return BinOp("-", Lit(0), self.parse_unary())
        return self.parse_primary()

    def parse_primary(self):
        t = self.next()
        if t.kind == "num":
            return Lit(float(t.value) if "." in t.value else int(t.value))
        if t.kind == "str":
            return Lit(t.value)
        if t.kind == "op" and t.value == "(":
            e = self.parse_expr()
            self.expect("op", ")")
            return e
        if t.kind == "op" and t.value == "*":
            return Star()
        if t.kind == "kw" and t.value == "CASE":
            whens = []
            while self.accept_kw("WHEN"):
                cond = self.parse_or()
                self.expect("kw", "THEN")
                whens.append((cond, self.parse_expr()))
            if not whens:
                raise SqlError(f"CASE needs at least one WHEN at {t.pos}")
            default = None
            if self.accept_kw("ELSE"):
                default = self.parse_expr()
            self.expect("kw", "END")
            return Case(tuple(whens), default)
        if t.kind == "ident":
            if self.peek().kind == "op" and self.peek().value == "(":
                self.next()
                args = []
                distinct = False
                if self.accept_kw("DISTINCT"):
                    distinct = True
                if not (self.peek().kind == "op" and self.peek().value == ")"):
                    args.append(self.parse_expr())
                    while self.peek().value == ",":
                        self.next()
                        args.append(self.parse_expr())
                self.expect("op", ")")
                return Func(t.value.upper(), tuple(args),
                            distinct=distinct)
            return Col(t.value)
        raise SqlError(f"unexpected {t.value!r} at {t.pos}")


    def parse_show(self) -> Show:
        self.expect("kw", "SHOW")
        t = self.next()
        if t.kind != "ident":
            raise SqlError(f"expected SHOW target at {t.pos}")
        what = t.value.lower()
        if what in ("databases", "tables"):
            stmt = Show(what)
        elif what in ("tags", "metrics"):
            self.expect("kw", "FROM")
            stmt = Show(what, self.expect("ident").value)
        else:
            raise SqlError(f"cannot SHOW {t.value!r}")
        if self.peek().kind != "eof":
            t2 = self.peek()
            raise SqlError(f"trailing input at {t2.pos}: {t2.value!r}")
        return stmt


def parse(sql: str) -> Select:
    return _Parser(tokenize(sql)).parse_select()


def parse_statement(sql: str) -> Select | Show | Explain:
    """Entry point that also accepts SHOW and EXPLAIN statements."""
    toks = tokenize(sql)
    if toks and toks[0].kind == "kw" and toks[0].value == "SHOW":
        return _Parser(toks).parse_show()
    # EXPLAIN/ANALYZE are not reserved words (they tokenize as idents so
    # columns may use the names); only the statement head position is
    # sniffed, exactly like real dialects treat soft keywords
    if (toks and toks[0].kind == "ident"
            and toks[0].value.upper() == "EXPLAIN"):
        k = 1
        analyze = (len(toks) > 1 and toks[1].kind == "ident"
                   and toks[1].value.upper() == "ANALYZE")
        if analyze:
            k = 2
        if k >= len(toks) or toks[k].kind == "eof":
            raise SqlError("EXPLAIN needs a SELECT statement")
        inner = _Parser(toks[k:]).parse_select()
        return Explain(inner, analyze=analyze, sql=sql[toks[k].pos:])
    return _Parser(toks).parse_select()


def expr_name(e) -> str:
    """Canonical display name of an expression."""
    if isinstance(e, Col):
        return e.name
    if isinstance(e, Lit):
        return repr(e.value)
    if isinstance(e, Star):
        return "*"
    if isinstance(e, Func):
        inner = ", ".join(expr_name(a) for a in e.args)
        if e.distinct:
            inner = f"DISTINCT {inner}"
        return f"{e.name}({inner})"
    if isinstance(e, Case):
        parts = " ".join(
            f"WHEN {expr_name(c)} THEN {expr_name(v)}"
            for c, v in e.whens)
        tail = f" ELSE {expr_name(e.default)}" if e.default is not None \
            else ""
        return f"CASE {parts}{tail} END"
    if isinstance(e, BinOp):
        return f"{expr_name(e.left)} {e.op} {expr_name(e.right)}"
    if isinstance(e, Not):
        return f"NOT {expr_name(e.expr)}"
    return str(e)


def contains_agg(e) -> bool:
    if isinstance(e, Func):
        if e.name in AGG_FUNCS:
            return True
        return any(contains_agg(a) for a in e.args)
    if isinstance(e, BinOp):
        return contains_agg(e.left) or (
            not isinstance(e.right, tuple) and contains_agg(e.right))
    if isinstance(e, Not):
        return contains_agg(e.expr)
    if isinstance(e, Case):
        return any(contains_agg(c) or contains_agg(v)
                   for c, v in e.whens) or (
            e.default is not None and contains_agg(e.default))
    return False
