"""Flame-graph tree assembly from folded stacks.

Reference analog: server/querier/profile/service/profile.go:113
(GenerateProfile: SQL over in_process_profile -> location tree with self/total
values) and :308 (newProfileTreeNode).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from deepflow_tpu.store.table import ColumnarTable

SEP = ";"


@dataclass
class FlameNode:
    name: str
    total_value: int = 0
    self_value: int = 0
    children: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "total_value": int(self.total_value),
            "self_value": int(self.self_value),
            "children": [c.to_dict() for c in
                         sorted(self.children.values(),
                                key=lambda n: -n.total_value)],
        }


def build_flame_tree(stacks: list[str], values: list[int],
                     root_name: str = "root") -> FlameNode:
    """Merge folded stacks ("a;b;c") weighted by values into a tree."""
    root = FlameNode(root_name)
    for stack, value in zip(stacks, values):
        if not stack:
            continue
        root.total_value += value
        node = root
        for frame in stack.split(SEP):
            child = node.children.get(frame)
            if child is None:
                child = FlameNode(frame)
                node.children[frame] = child
            child.total_value += value
            node = child
        node.self_value += value
    return root


def trace_flame_stacks(tree: dict) -> tuple[list[str], list[int]]:
    """An assembled trace tree (build_trace_from_spans output) as folded
    stacks weighted by SELF time ns — each span's duration minus the
    time covered by its children, so the flame graph shows where a
    query (or request) actually spent its wall clock. Feed the result
    to build_flame_tree."""
    stacks: list[str] = []
    values: list[int] = []

    def walk(node: dict, prefix: str) -> None:
        label = f"{node['service']}:{node['name']}" \
            if node.get("service") else node["name"]
        path = f"{prefix}{SEP}{label}" if prefix else label
        child_ns = sum(int(c.get("duration_ns", 0))
                       for c in node.get("children", []))
        self_ns = max(0, int(node.get("duration_ns", 0)) - child_ns)
        if self_ns:
            stacks.append(path)
            values.append(self_ns)
        for c in node.get("children", []):
            walk(c, path)

    for root in tree.get("spans", []):
        walk(root, "")
    return stacks, values


def profile_stack_values(table: ColumnarTable,
                         time_start_ns: int | None = None,
                         time_end_ns: int | None = None,
                         event_type: str | None = None,
                         app_service: str | None = None,
                         profiler: str | None = None,
                         stack_col: str = "stack",
                         value_col: str = "value") -> tuple[list, list]:
    """Per-stack aggregated (folded_stacks, values) — the pre-tree form.

    This is the cluster-federation unit: each shard aggregates in its
    own encoded space, DECODES the surviving unique stacks, and the
    coordinator sums by stack string before one build_flame_tree — the
    stack ids themselves are shard-local and never merged.

    Aggregates by stack *in encoded space* (SmartEncoding: group by the
    dictionary id, decode only the surviving unique stacks).
    """
    chunks = table.snapshot()
    spec = table.columns[stack_col]
    d = table.dicts[stack_col]
    agg: dict[int, int] = {}
    etype_code = None
    if event_type is not None:
        etype_code = table.columns["event_type"].enum_of(event_type)
    svc_code = None
    if app_service is not None:
        svc_code = table.dicts["app_service"].lookup(app_service)
        if svc_code is None:
            return [], []
    prof_code = None
    if profiler is not None:
        prof_code = table.dicts["profiler"].lookup(profiler)
        if prof_code is None:
            return [], []
    for ch in chunks:
        mask = np.ones(len(ch[stack_col]), dtype=bool)
        if time_start_ns is not None:
            mask &= ch["time"] >= time_start_ns
        if time_end_ns is not None:
            mask &= ch["time"] < time_end_ns
        if etype_code is not None:
            mask &= ch["event_type"] == etype_code
        if svc_code is not None:
            mask &= ch["app_service"] == svc_code
        if prof_code is not None:
            mask &= ch["profiler"] == prof_code
        sids = ch[stack_col][mask]
        vals = ch[value_col][mask]
        if not len(sids):
            continue
        uniq, inv = np.unique(sids, return_inverse=True)
        sums = np.bincount(inv, weights=vals.astype(np.float64))
        for sid, v in zip(uniq.tolist(), sums.tolist()):
            agg[sid] = agg.get(sid, 0) + int(v)
    stacks = [d.decode(sid) for sid in agg]
    return stacks, list(agg.values())


def merge_stack_values(parts: list[tuple[list, list]]) -> tuple[list, list]:
    """Sum per-shard (stacks, values) aggregates by stack string."""
    agg: dict[str, int] = {}
    for stacks, values in parts:
        for s, v in zip(stacks, values):
            agg[s] = agg.get(s, 0) + int(v)
    return list(agg.keys()), list(agg.values())


def profile_flame_tree(table: ColumnarTable,
                       time_start_ns: int | None = None,
                       time_end_ns: int | None = None,
                       event_type: str | None = None,
                       app_service: str | None = None,
                       profiler: str | None = None,
                       stack_col: str = "stack",
                       value_col: str = "value") -> FlameNode:
    """Flame tree straight off the in_process_profile table."""
    stacks, values = profile_stack_values(
        table, time_start_ns=time_start_ns, time_end_ns=time_end_ns,
        event_type=event_type, app_service=app_service, profiler=profiler,
        stack_col=stack_col, value_col=value_col)
    return build_flame_tree(stacks, values)
