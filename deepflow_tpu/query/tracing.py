"""Distributed-trace assembly: l7 flow logs (+ TPU device spans) -> trace tree.

Reference analog: server/querier/app/distributed_tracing (TraceMap built from
trace_tree) and the query-time stitching of SURVEY.md §3.3: spans join on
trace_id / span ids, with time containment as the fallback, and (TPU-native
twist) device HLO spans overlay onto the host span that dispatched them.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from deepflow_tpu.store.table import ColumnarTable


@dataclass
class TraceSpan:
    span_id: str
    parent_span_id: str
    name: str
    service: str
    l7_protocol: str
    start_ns: int
    end_ns: int
    status: str
    response_code: int
    ip_src: str = ""
    ip_dst: str = ""
    kind: str = "network"       # network | device
    attrs: dict = field(default_factory=dict)
    children: list = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "name": self.name,
            "service": self.service,
            "l7_protocol": self.l7_protocol,
            "start_ns": int(self.start_ns),
            "end_ns": int(self.end_ns),
            "duration_ns": int(self.end_ns - self.start_ns),
            "status": self.status,
            "response_code": int(self.response_code),
            "ip_src": self.ip_src,
            "ip_dst": self.ip_dst,
            "kind": self.kind,
            "attrs": self.attrs,
            "children": [c.to_dict() for c in
                         sorted(self.children, key=lambda s: s.start_ns)],
        }


def _rows(table: ColumnarTable, mask_fn) -> list[dict]:
    out = []
    for ch in table.snapshot():
        if not ch:
            continue
        n = len(next(iter(ch.values())))
        if n == 0:
            continue
        mask = mask_fn(ch)
        idx = np.flatnonzero(mask)
        for i in idx.tolist():
            row = {}
            for name, arr in ch.items():
                spec = table.columns[name]
                v = arr[i]
                if spec.kind == "str":
                    row[name] = table.dicts[name].decode(int(v))
                elif spec.kind == "enum":
                    row[name] = spec.enum_values[int(v)]
                else:
                    row[name] = int(v)
            out.append(row)
    return out


def scan_trace_spans(l7_table: ColumnarTable, trace_id: str) -> list[dict]:
    """One shard's raw span dicts for a trace, scanned from l7_flow_log.
    The dict shape feeds build_trace_from_spans, so a cluster coordinator
    can pool span dicts from every shard (spans of one trace may land on
    many shards) and assemble once — dedup is by (span_id, start_ns,
    flow_id) there."""
    tid_code = l7_table.dicts["trace_id"].lookup(trace_id)
    if tid_code is None:
        return []
    rows = _rows(l7_table, lambda ch: ch["trace_id"] == tid_code)
    spans: list[dict] = []
    for r in rows:
        name = r["endpoint"] or r["request_resource"] or r["request_type"]
        spans.append({
            "span_id": (r["span_id"]
                        or f"flow-{r['flow_id']}-{r['request_id']}"),
            "parent_span_id": r["parent_span_id"],
            "name": f"{r['request_type']} {name}".strip(),
            "service": r.get("app_service") or r.get("host", ""),
            "l7_protocol": r["l7_protocol"],
            "start_ns": r["time"],
            "end_ns": r["time"] + r["response_duration"],
            "status": r["response_status"],
            "response_code": r["response_code"],
            "ip_src": r["ip_src"], "ip_dst": r["ip_dst"],
            "flow_id": r["flow_id"],
            "x_request_id": r["x_request_id"],
        })
    return spans


def build_trace(l7_table: ColumnarTable, trace_id: str,
                tpu_table: ColumnarTable | None = None,
                max_spans: int = 1000) -> dict:
    """Assemble the trace tree for one trace_id by scanning l7_flow_log.

    This is the FALLBACK path (standalone library use, or data not yet
    precomputed); the server prefers build_trace_from_spans over the
    ingest-time flow_log.trace_tree rows."""
    spans = scan_trace_spans(l7_table, trace_id)
    if not spans:
        return {"trace_id": trace_id, "spans": [], "span_count": 0,
                "truncated": False}
    return build_trace_from_spans(trace_id, spans, tpu_table, max_spans)


def build_trace_from_spans(trace_id: str, span_dicts: list[dict],
                           tpu_table: ColumnarTable | None = None,
                           max_spans: int = 1000) -> dict:
    """Assemble from precomputed span dicts (flow_log.trace_tree rows +
    TraceTreeBuilder pending spans) — touches ONLY this trace's data.
    Reference: querier reading ingester-written trace_tree
    (libs/tracetree/tracetree.go:47)."""
    spans: list[TraceSpan] = []
    seen: set = set()
    for d in span_dicts:
        key = (d.get("span_id", ""), int(d.get("start_ns", 0)),
               int(d.get("flow_id", 0)))
        if key in seen:  # straggler rows can duplicate a span
            continue
        seen.add(key)
        # query-trace spans (kind="query") carry their own attrs dict;
        # flow spans get the classic flow identity pair
        attrs = d.get("attrs")
        if not isinstance(attrs, dict):
            attrs = {"flow_id": d.get("flow_id", 0),
                     "x_request_id": d.get("x_request_id", "")}
        spans.append(TraceSpan(
            span_id=d.get("span_id", ""),
            parent_span_id=d.get("parent_span_id", ""),
            name=d.get("name", ""),
            service=d.get("service", ""),
            l7_protocol=str(d.get("l7_protocol", "")),
            start_ns=int(d.get("start_ns", 0)),
            end_ns=int(d.get("end_ns", 0)),
            status=str(d.get("status", "unknown")),
            response_code=int(d.get("response_code", 0)),
            ip_src=d.get("ip_src", ""), ip_dst=d.get("ip_dst", ""),
            kind=str(d.get("kind", "network")),
            attrs=attrs,
        ))
    return _assemble(trace_id, spans, tpu_table, max_spans)


def _assemble(trace_id: str, spans: list[TraceSpan],
              tpu_table: ColumnarTable | None,
              max_spans: int) -> dict:
    total = len(spans)
    truncated = total > max_spans
    if truncated:
        # deterministic: keep the earliest spans, report the cut
        spans = sorted(spans, key=lambda s: s.start_ns)[:max_spans]
    spans.sort(key=lambda s: (s.start_ns, -(s.end_ns - s.start_ns)))

    # explicit parent links first
    by_id = {s.span_id: s for s in spans if s.span_id}
    roots: list[TraceSpan] = []
    unparented: list[TraceSpan] = []
    for s in spans:
        parent = by_id.get(s.parent_span_id) if s.parent_span_id else None
        if parent is not None and parent is not s:
            parent.children.append(s)
        else:
            unparented.append(s)
    # fallback: time containment (client span encloses server span)
    for s in unparented:
        best = None
        for cand in spans:
            if cand is s:
                continue
            if cand.start_ns <= s.start_ns and s.end_ns <= cand.end_ns and \
                    (cand.end_ns - cand.start_ns) > (s.end_ns - s.start_ns):
                if best is None or (cand.end_ns - cand.start_ns) < \
                        (best.end_ns - best.start_ns):
                    best = cand
        if best is not None:
            best.children.append(s)
        else:
            roots.append(s)

    # overlay TPU device spans: ONE scan over the whole trace window, each
    # device span attached to the tightest containing leaf only
    leaves = [s for s in spans if not s.children]
    if tpu_table is not None and len(tpu_table) and leaves:
        lo = min(s.start_ns for s in leaves)
        hi = max(s.end_ns for s in leaves)
        device_kinds = (1, 2, 3)  # compute/collective/transfer only

        def in_window(ch):
            t = ch["time"]
            return ((t >= lo) & (t < hi)
                    & np.isin(ch["kind"], device_kinds))

        dev_rows = _rows(tpu_table, in_window)[:50 * len(leaves)]
        for r in dev_rows:
            t = r["time"]
            best = None
            for s in leaves:
                if s.start_ns <= t < s.end_ns:
                    if best is None or (s.end_ns - s.start_ns) < \
                            (best.end_ns - best.start_ns):
                        best = s
            if best is None:
                continue
            best.children.append(TraceSpan(
                span_id=f"hlo-{r['run_id']}-{r['hlo_op']}",
                parent_span_id=best.span_id,
                name=r["hlo_op"] or r["hlo_module"],
                service=f"tpu-device-{r['device_id']}",
                l7_protocol="",
                start_ns=r["time"],
                end_ns=r["time"] + r["duration_ns"],
                status="ok",
                response_code=0,
                kind="device",
                attrs={"hlo_category": r["hlo_category"],
                       "collective": r["collective"],
                       "flops": r["flops"]},
            ))

    return {
        "trace_id": trace_id,
        "span_count": total,
        "truncated": truncated,
        "spans": [s.to_dict() for s in
                  sorted(roots, key=lambda s: s.start_ns)],
    }


def build_syscall_trace(l7_table: ColumnarTable, syscall_trace_id: int,
                        max_hops: int = 16) -> dict:
    """Trace assembly WITHOUT W3C headers: follow thread-scoped syscall
    chain ids (reference socket_trace.bpf.c:1291) hop by hop.

    An ingress request assigns a chain id T to its thread; every egress the
    thread performs before its next ingress (the downstream calls the
    request caused) carries T. So rows sharing a syscall_trace_id_request
    or _response belong to one causal chain; each hop's response-side id
    chains to the next window of work.
    """
    seen_ids: set[int] = set()
    frontier = {int(syscall_trace_id)}
    rows: dict[tuple, dict] = {}
    for _ in range(max_hops):
        frontier = {t for t in frontier if t and t not in seen_ids}
        if not frontier:
            break
        seen_ids.update(frontier)
        ids = list(frontier)

        def match(ch, ids=ids):
            import numpy as np
            m = np.isin(ch["syscall_trace_id_request"], ids)
            m |= np.isin(ch["syscall_trace_id_response"], ids)
            return m

        frontier = set()
        for r in _rows(l7_table, match):
            key = (r["flow_id"], r["time"], r["request_id"])
            if key in rows:
                continue
            rows[key] = r
            frontier.add(int(r["syscall_trace_id_request"]))
            frontier.add(int(r["syscall_trace_id_response"]))

    spans = []
    for r in rows.values():
        name = r["endpoint"] or r["request_resource"] or r["request_type"]
        spans.append(TraceSpan(
            span_id=f"flow-{r['flow_id']}-{r['time']}",
            parent_span_id="",
            name=f"{r['request_type']} {name}".strip(),
            service=r.get("app_service") or r.get("host", ""),
            l7_protocol=r["l7_protocol"],
            start_ns=r["time"],
            end_ns=r["time"] + max(r["response_duration"], 1),
            status=r["response_status"],
            response_code=r["response_code"],
            ip_src=r["ip_src"], ip_dst=r["ip_dst"],
            attrs={
                "syscall_trace_id_request":
                    int(r["syscall_trace_id_request"]),
                "syscall_trace_id_response":
                    int(r["syscall_trace_id_response"]),
            }))
    spans.sort(key=lambda s: s.start_ns)
    # parenting: a span is the child of the span whose REQUEST chain id it
    # shares and which started earlier (the ingress that caused it);
    # fallback to time containment
    roots: list[TraceSpan] = []
    for i, s in enumerate(spans):
        parent = None
        for cand in spans[:i]:
            if cand.attrs["syscall_trace_id_request"] and \
                    cand.attrs["syscall_trace_id_request"] == \
                    s.attrs["syscall_trace_id_request"]:
                parent = cand
        if parent is None:
            for cand in spans[:i]:
                if cand.start_ns <= s.start_ns and \
                        s.end_ns <= cand.end_ns:
                    parent = cand
        if parent is not None:
            parent.children.append(s)
        else:
            roots.append(s)
    return {
        "syscall_trace_id": int(syscall_trace_id),
        "span_count": len(spans),
        "spans": [s.to_dict() for s in roots],
    }
