"""PromQL engine over the metric tables.

Reference analog: server/querier/app/prometheus (the reference embeds the
full upstream promql engine over DeepFlow storage,
querier/app/prometheus/router/router.go:40-41). This is a from-scratch
engine with the upstream language surface Grafana panels and alert rules
actually use:

- selectors with =, !=, =~, !~ matchers, [range], offset
- binary ops between vectors with vector matching: on/ignoring,
  group_left/group_right, bool modifier; and/or/unless set ops;
  arithmetic + - * / % ^ and comparisons == != > < >= <=
- aggregations: sum avg min max count group stddev stdvar topk bottomk
  quantile count_values, with by/without
- range functions: rate irate increase delta idelta deriv predict_linear
  changes resets absent_over_time and the *_over_time family
  (avg/min/max/sum/count/last/present/stddev/stdvar/quantile)
- instant functions: histogram_quantile, clamp*, abs/ceil/floor/round,
  exp/ln/log2/log10/sqrt/sgn, scalar/vector/time/timestamp, absent,
  label_replace/label_join, sort/sort_desc
- subqueries expr[range:step]

Counter semantics are storage-aware: remote-write `prometheus.samples` and
`deepflow_system` snapshots hold CUMULATIVE counters (Prometheus-style
extrapolated rate with reset detection), while the internal flow_metrics
tables hold per-interval DELTA samples (rate = sum/range). Subquery results
feed rate() with cumulative semantics, matching upstream.

Metric naming: <family>_<column>, e.g. flow_metrics_network_byte_tx, plus
any remote-write metric name and deepflow_system self-telemetry.
"""

from __future__ import annotations

import json as _json
import math
import re
from dataclasses import dataclass, field

import numpy as np

from deepflow_tpu.store.db import Database

_DUR_PART = re.compile(r"(\d+)(ms|s|m|h|d|w|y)")
_DUR_FULL = re.compile(r"^(?:\d+(?:ms|s|m|h|d|w|y))+$")
_DUR_S = {"ms": 0.001, "s": 1, "m": 60, "h": 3600, "d": 86400,
          "w": 604800, "y": 31536000}

_LOOKBACK_S = 300  # Prometheus staleness lookback

# metric prefix -> (table, tag label columns)
_NETWORK_TAGS = ["ip_src", "ip_dst", "server_port", "protocol", "host",
                 "pod_name", "tpu_pod", "slice_id", "agent_id"]
_APP_TAGS = ["ip_src", "ip_dst", "server_port", "l7_protocol", "app_service",
             "host", "pod_name", "tpu_pod", "slice_id", "agent_id"]

_FAMILIES = {
    "flow_metrics_network_": ("flow_metrics.network.1s", _NETWORK_TAGS),
    "flow_metrics_application_": ("flow_metrics.application.1s", _APP_TAGS),
}

# narrow-format (metric_name/value_name/value) sources served by prefix:
# self-telemetry and telegraf/external metrics share one storage shape
_NARROW_TABLES = (
    ("deepflow_system_", "deepflow_system.deepflow_system"),
    ("ext_metrics_", "ext_metrics.metrics"),
)


class PromqlError(Exception):
    pass


class UnknownMetricError(PromqlError):
    """A selector naming a metric nothing has ingested — matches nothing
    (metadata endpoints treat this as empty, not as a bad request)."""


def parse_duration_s(s: str) -> float:
    if not _DUR_FULL.match(s):
        raise PromqlError(f"bad duration {s!r}")
    return sum(int(n) * _DUR_S[u] for n, u in _DUR_PART.findall(s))


_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", "\\": "\\", '"': '"',
            "'": "'", "a": "\a", "b": "\b", "f": "\f", "v": "\v", "0": "\0"}


def _unquote(raw: str) -> str:
    """Strip quotes and process Go-style escape sequences (\\n, \\\",
    \\xHH, \\uHHHH) — Grafana emits escaped regexes like "ns\\\\.svc"
    routinely."""
    body = raw[1:-1]
    if "\\" not in body:
        return body
    out, i = [], 0
    while i < len(body):
        c = body[i]
        if c != "\\" or i + 1 >= len(body):
            out.append(c)
            i += 1
            continue
        e = body[i + 1]
        if e in _ESCAPES:
            out.append(_ESCAPES[e])
            i += 2
        elif e == "x" and i + 3 < len(body):
            try:
                out.append(chr(int(body[i + 2:i + 4], 16)))
                i += 4
            except ValueError:
                raise PromqlError(f"bad escape in string: \\x"
                                  f"{body[i + 2:i + 4]!r}") from None
        elif e == "u" and i + 5 < len(body):
            try:
                out.append(chr(int(body[i + 2:i + 6], 16)))
                i += 6
            except ValueError:
                raise PromqlError(f"bad escape in string: \\u"
                                  f"{body[i + 2:i + 6]!r}") from None
        else:
            # unknown escape: keep verbatim (lenient where upstream errors)
            out.append(c)
            out.append(e)
            i += 2
    return "".join(out)


# -- AST ---------------------------------------------------------------------

@dataclass
class VectorSelector:
    metric: str
    matchers: list = field(default_factory=list)  # (label, op, value)
    offset_s: float = 0.0


@dataclass
class MatrixSelector:
    vs: VectorSelector
    range_s: float


@dataclass
class Subquery:
    expr: object
    range_s: float
    step_s: float  # 0 -> default resolution
    offset_s: float = 0.0


@dataclass
class Num:
    value: float


@dataclass
class Str:
    value: str


@dataclass
class Call:
    fn: str
    args: list


@dataclass
class Agg:
    op: str
    expr: object
    grouping: list = field(default_factory=list)
    without: bool = False
    param: object = None


@dataclass
class VectorMatching:
    on: bool = False
    labels: list = field(default_factory=list)
    card: str = "one-to-one"  # one-to-one | many-to-one | one-to-many
    include: list = field(default_factory=list)


@dataclass
class BinOp:
    op: str
    lhs: object
    rhs: object
    bool_mod: bool = False
    matching: VectorMatching | None = None


@dataclass
class Unary:
    op: str
    expr: object


_AGG_OPS = {"sum", "avg", "min", "max", "count", "group", "stddev", "stdvar",
            "topk", "bottomk", "quantile", "count_values"}
_PARAM_AGGS = {"topk", "bottomk", "quantile", "count_values"}

_RANGE_FNS = {
    "rate", "irate", "increase", "delta", "idelta", "deriv",
    "predict_linear", "changes", "resets", "absent_over_time",
    "avg_over_time", "min_over_time", "max_over_time", "sum_over_time",
    "count_over_time", "last_over_time", "present_over_time",
    "stddev_over_time", "stdvar_over_time", "quantile_over_time",
}
_MATH_FNS = {"abs": np.abs, "ceil": np.ceil, "floor": np.floor,
             "exp": np.exp, "ln": np.log, "log2": np.log2,
             "log10": np.log10, "sqrt": np.sqrt, "sgn": np.sign}
_INSTANT_FNS = _MATH_FNS.keys() | {
    "round", "clamp", "clamp_min", "clamp_max", "histogram_quantile",
    "scalar", "vector", "time", "timestamp", "absent", "label_replace",
    "label_join", "sort", "sort_desc"}
_FNS = _RANGE_FNS | _INSTANT_FNS

_CMP_OPS = {"==", "!=", ">", "<", ">=", "<="}
_SET_OPS = {"and", "or", "unless"}

# precedence (binding power), upstream promql/parser
_PRECEDENCE = {"or": 1, "and": 2, "unless": 2,
               "==": 3, "!=": 3, "<=": 3, "<": 3, ">=": 3, ">": 3,
               "+": 4, "-": 4, "*": 5, "/": 5, "%": 5, "^": 6}
_RIGHT_ASSOC = {"^"}


# -- lexer -------------------------------------------------------------------

_TOKEN = re.compile(r"""
    (?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)
  | (?P<lbrace>\{) | (?P<rbrace>\})
  | (?P<lparen>\() | (?P<rparen>\))
  | (?P<lbrack>\[) | (?P<rbrack>\])
  | (?P<str>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*')
  | (?P<num>\d+\.\d+(?:[eE][+-]?\d+)?|\d+[eE][+-]?\d+|\.\d+|\d+)
  | (?P<op>==|!=|<=|>=|=~|!~|<|>|=|,|\*|/|%|\^|\+|-|:|@)
  | (?P<ws>\s+)
""", re.VERBOSE)


def _tokens(q: str):
    out, i = [], 0
    while i < len(q):
        m = _TOKEN.match(q, i)
        if not m:
            raise PromqlError(f"bad token at {i}: {q[i:i+10]!r}")
        i = m.end()
        if m.lastgroup != "ws":
            out.append((m.lastgroup, m.group()))
    return out


# -- parser ------------------------------------------------------------------

class _Parser:
    def __init__(self, q: str):
        self.toks = _tokens(q)
        self.pos = 0

    def peek(self, k: int = 0):
        i = self.pos + k
        return self.toks[i] if i < len(self.toks) else ("eof", "")

    def next_(self):
        t = self.peek()
        self.pos += 1
        return t

    def expect(self, kind: str, text: str | None = None):
        t = self.next_()
        if t[0] != kind or (text is not None and t[1] != text):
            raise PromqlError(f"expected {text or kind}, got {t[1]!r}")
        return t

    def at_name(self, *names: str) -> bool:
        t = self.peek()
        return t[0] == "name" and t[1] in names

    def _split_colon_names(self) -> None:
        """Metric names may contain ':' (recording rules), so the lexer
        folds ':' into name tokens — but inside [range:step] the ':' is a
        separator. Re-split name tokens containing ':' up to the next ']'."""
        i = self.pos
        while i < len(self.toks) and self.toks[i][0] != "rbrack":
            kind, text = self.toks[i]
            if kind == "name" and ":" in text:
                repl = []
                for j, part in enumerate(text.split(":")):
                    if j:
                        repl.append(("op", ":"))
                    if part:
                        repl.extend(_tokens(part))
                self.toks[i:i + 1] = repl
                i += len(repl)
            else:
                i += 1

    # duration: "5m" lexes as num+name, "1h30m" as num+name("h30m");
    # join adjacent tokens while the concatenation is a valid duration
    def parse_duration(self) -> float:
        parts = [self.expect("num")[1]]
        while True:
            t = self.peek()
            cand = "".join(parts) + t[1]
            if t[0] in ("name", "num") and (
                    _DUR_FULL.match(cand)
                    or (t[0] == "num" and _DUR_FULL.match(cand + "s"))):
                parts.append(self.next_()[1])
                if t[0] == "num":
                    continue
                if _DUR_FULL.match("".join(parts)) and not (
                        self.peek()[0] == "num"):
                    break
            else:
                break
        return parse_duration_s("".join(parts))

    def parse_label_list(self) -> list[str]:
        self.expect("lparen")
        out = []
        while self.peek()[0] != "rparen":
            out.append(self.expect("name")[1])
            if self.peek() == ("op", ","):
                self.next_()
        self.expect("rparen")
        return out

    def parse_matchers(self) -> list:
        matchers = []
        self.expect("lbrace")
        while self.peek()[0] != "rbrace":
            lbl = self.expect("name")[1]
            op = self.expect("op")[1]
            if op == "==":  # tolerate common typo? no: strict
                raise PromqlError("bad matcher op ==")
            if op not in ("=", "!=", "=~", "!~"):
                raise PromqlError(f"bad matcher op {op}")
            val = _unquote(self.expect("str")[1])
            if op in ("=~", "!~"):
                _compile(val)  # bad regex fails at parse time (upstream)
            matchers.append((lbl, op, val))
            if self.peek() == ("op", ","):
                self.next_()
        self.expect("rbrace")
        return matchers

    def parse_expr(self, min_prec: int = 0):
        lhs = self.parse_unary()
        while True:
            t = self.peek()
            op = None
            if t[0] == "op" and t[1] in _PRECEDENCE:
                op = t[1]
            elif t[0] == "name" and t[1] in _SET_OPS:
                op = t[1]
            if op is None:
                break
            prec = _PRECEDENCE[op]
            if prec < min_prec:
                break
            self.next_()
            bool_mod = False
            if self.at_name("bool"):
                self.next_()
                bool_mod = True
                if op not in _CMP_OPS:
                    raise PromqlError("bool modifier on non-comparison")
            matching = None
            if self.at_name("on", "ignoring"):
                on = self.next_()[1] == "on"
                matching = VectorMatching(on=on, labels=self.parse_label_list())
                if self.at_name("group_left", "group_right"):
                    gl = self.next_()[1] == "group_left"
                    matching.card = "many-to-one" if gl else "one-to-many"
                    if self.peek()[0] == "lparen":
                        matching.include = self.parse_label_list()
            next_min = prec + (0 if op in _RIGHT_ASSOC else 1)
            rhs = self.parse_expr(next_min)
            lhs = BinOp(op=op, lhs=lhs, rhs=rhs, bool_mod=bool_mod,
                        matching=matching)
        return lhs

    def parse_unary(self):
        t = self.peek()
        # ^ binds tighter than unary: -2^2 == -(2^2), per upstream
        if t == ("op", "-"):
            self.next_()
            return Unary("-", self.parse_expr(_PRECEDENCE["^"]))
        if t == ("op", "+"):
            self.next_()
            return self.parse_expr(_PRECEDENCE["^"])
        return self.parse_postfix(self.parse_atom())

    def parse_postfix(self, expr):
        while True:
            t = self.peek()
            if t[0] == "lbrack":
                self.next_()
                self._split_colon_names()
                range_s = self.parse_duration()
                if self.peek() == ("op", ":"):
                    self.next_()
                    step_s = 0.0
                    if self.peek()[0] != "rbrack":
                        step_s = self.parse_duration()
                    self.expect("rbrack")
                    expr = Subquery(expr=expr, range_s=range_s, step_s=step_s)
                else:
                    self.expect("rbrack")
                    if not isinstance(expr, VectorSelector):
                        raise PromqlError(
                            "[range] is only valid on a selector "
                            "(use [range:step] for subqueries)")
                    expr = MatrixSelector(vs=expr, range_s=range_s)
            elif self.at_name("offset"):
                self.next_()
                neg = False
                if self.peek() == ("op", "-"):
                    self.next_()
                    neg = True
                off = self.parse_duration() * (-1 if neg else 1)
                if isinstance(expr, VectorSelector):
                    expr.offset_s = off
                elif isinstance(expr, MatrixSelector):
                    expr.vs.offset_s = off
                elif isinstance(expr, Subquery):
                    expr.offset_s = off
                else:
                    raise PromqlError("offset on non-selector")
            else:
                break
        return expr

    def parse_atom(self):
        t = self.peek()
        if t[0] == "lparen":
            self.next_()
            inner = self.parse_expr()
            self.expect("rparen")
            return inner
        if t[0] == "num":
            self.next_()
            return Num(float(t[1]))
        if t[0] == "str":
            self.next_()
            return Str(_unquote(t[1]))
        if t[0] == "op" and t[1] in ("+", "-"):
            return self.parse_unary()
        if t[0] != "name":
            raise PromqlError(f"unexpected {t[1]!r}")
        name = t[1]
        if name in ("Inf", "inf", "+Inf"):
            self.next_()
            return Num(math.inf)
        if name in ("NaN", "nan"):
            self.next_()
            return Num(math.nan)
        if name in _AGG_OPS and self.peek(1)[0] in ("lparen", "name"):
            return self.parse_agg()
        if name in _FNS and self.peek(1)[0] == "lparen":
            self.next_()
            self.expect("lparen")
            args = []
            while self.peek()[0] != "rparen":
                args.append(self.parse_expr())
                if self.peek() == ("op", ","):
                    self.next_()
            self.expect("rparen")
            return Call(fn=name, args=args)
        # plain selector
        self.next_()
        sel = VectorSelector(metric=name)
        if self.peek()[0] == "lbrace":
            sel.matchers = self.parse_matchers()
        return sel

    def parse_agg(self):
        op = self.next_()[1]
        grouping, without = [], False
        if self.at_name("by", "without"):
            without = self.next_()[1] == "without"
            grouping = self.parse_label_list()
        self.expect("lparen")
        first = self.parse_expr()
        param = None
        if self.peek() == ("op", ","):
            self.next_()
            param = first
            first = self.parse_expr()
        self.expect("rparen")
        if param is None and op in _PARAM_AGGS:
            raise PromqlError(f"{op}() needs a parameter")
        if self.at_name("by", "without"):
            without = self.next_()[1] == "without"
            grouping = self.parse_label_list()
        return Agg(op=op, expr=first, grouping=grouping, without=without,
                   param=param)


# fn -> (min_args, max_args); None max = unbounded
_ARITY = {"histogram_quantile": (2, 2), "label_replace": (5, 5),
          "clamp": (3, 3), "clamp_min": (2, 2), "clamp_max": (2, 2),
          "quantile_over_time": (2, 2), "predict_linear": (2, 2),
          "vector": (1, 1), "scalar": (1, 1), "time": (0, 0),
          "round": (1, 2), "label_join": (3, None)}
_DEFAULT_ARITY = (1, 1)


def _validate(node) -> None:
    if isinstance(node, Call):
        lo, hi = _ARITY.get(node.fn, _DEFAULT_ARITY)
        if len(node.args) < lo or (hi is not None and len(node.args) > hi):
            raise PromqlError(
                f"{node.fn}() takes "
                f"{lo if lo == hi else f'{lo}+' if hi is None else f'{lo}-{hi}'}"
                f" argument(s), got {len(node.args)}")
        if node.fn in _RANGE_FNS:
            idx = 1 if node.fn == "quantile_over_time" else 0
            if idx >= len(node.args):
                raise PromqlError(f"{node.fn}() needs a range argument")
            arg = node.args[idx]
            if not isinstance(arg, (MatrixSelector, Subquery)):
                raise PromqlError(
                    f"{node.fn}() needs a [range] selector or subquery")
        for a in node.args:
            _validate(a)
    elif isinstance(node, Agg):
        _validate(node.expr)
        if node.param is not None:
            _validate(node.param)
    elif isinstance(node, BinOp):
        _validate(node.lhs)
        _validate(node.rhs)
    elif isinstance(node, Unary):
        _validate(node.expr)
    elif isinstance(node, Subquery):
        _validate(node.expr)


def parse(q: str):
    p = _Parser(q)
    ast = p.parse_expr()
    if p.peek()[0] != "eof":
        raise PromqlError(f"trailing input: {p.peek()[1]!r}")
    _validate(ast)
    return ast


# -- storage layer -----------------------------------------------------------

def _mangle(s: str) -> str:
    return "".join(c if c.isalnum() else "_" for c in s)


def _resolve_metric(db: Database, name: str):
    """-> (table, value_column, tag_columns, pre_filters, labels_col).

    pre_filters: [(column, code), ...] row filters identifying the metric;
    labels_col: json-encoded label column (series identity) or None.
    """
    # narrow-format tables: <prefix><metric>_<value> with dots mangled,
    # e.g. deepflow_system_agent_sender_sent_frames, ext_metrics_cpu_usage
    for prefix, tname in _NARROW_TABLES:
        if not name.startswith(prefix):
            continue
        table = db.table(tname)
        suffix = name[len(prefix):]
        mdict, vdict = table.dicts["metric_name"], table.dicts["value_name"]
        # longest metric-name match first: mangling can make one name a
        # prefix of another, and first-match would be ingest-order dependent
        candidates = sorted(enumerate(mdict.snapshot()),
                            key=lambda kv: -len(kv[1]))
        for mi, mn in candidates:
            if not mn or not suffix.startswith(_mangle(mn) + "_"):
                continue
            rest = suffix[len(_mangle(mn)) + 1:]
            for vi, vn in enumerate(vdict.snapshot()):
                if vn and _mangle(vn) == rest:
                    # host/agent_id stay real columns: series split per
                    # agent and matchable alongside the json tags
                    return (table, "value", ["tag_json", "host", "agent_id"],
                            [("metric_name", mi), ("value_name", vi)],
                            "tag_json")
        # fall through: a remote-write metric may share the prefix
    for prefix, (tname, tags) in _FAMILIES.items():
        if name.startswith(prefix):
            col = name[len(prefix):]
            table = db.table(tname)
            if col in table.columns:
                return table, col, tags, None, None
            break  # fall through: maybe a remote-write metric with a
            # name that happens to share the family prefix
    # remote-write samples: any metric name, labels in labels_json
    table = db.table("prometheus.samples")
    code = table.dicts["metric_name"].lookup(name)
    if code is None:
        raise UnknownMetricError(f"unknown metric {name!r}")
    return (table, "value", ["labels_json"], [("metric_name", code)],
            "labels_json")


def _compile(pattern: str):
    try:
        return re.compile(pattern)  # PromQL regexes are anchored (fullmatch)
    except re.error as e:
        raise PromqlError(f"bad regex {pattern!r}: {e}") from None


def _compile_matchers(table, matchers, labels_col):
    """Precompute chunk-independent matcher state -> per-chunk appliers.
    Dictionary scans and regex compilation happen ONCE, not per chunk."""
    appliers = []
    for lbl, op, val in matchers:
        negate = op in ("!=", "!~")
        # json-labeled metrics: remote-write user labels ALWAYS match via
        # the json column (they'd be shadowed by same-named universal tag
        # columns); self-telemetry prefers real columns (host/agent_id) and
        # falls back to the json tags. Exception: org_id is the tenancy
        # boundary — it must always hit the ingest-injected real column,
        # never a user-supplied label
        if labels_col is not None and lbl != "org_id" and (
                labels_col == "labels_json" or lbl not in table.columns):
            ids = _labels_json_ids(table, lbl, op, val, labels_col)
            appliers.append(("isin", labels_col, ids, negate))
            continue
        if lbl not in table.columns:
            raise PromqlError(f"unknown label {lbl!r}")
        spec = table.columns[lbl]
        if spec.kind == "str":
            if op in ("=", "!="):
                code = table.dicts[lbl].lookup(val)
                appliers.append((
                    "eq", lbl,
                    code if code is not None else 0xFFFFFFFF, negate))
            else:
                rx = _compile(val)
                ids = table.dicts[lbl].match_ids(
                    lambda s: rx.fullmatch(s) is not None)
                appliers.append(("isin", lbl, ids, negate))
        elif spec.kind == "enum":
            if op in ("=~", "!~"):
                rx = _compile(val)
                ids = np.asarray(
                    [i for i, s in enumerate(spec.enum_values)
                     if rx.fullmatch(s)], dtype=np.uint16)
                appliers.append(("isin", lbl, ids, negate))
            else:
                try:
                    idx = spec.enum_values.index(val)
                except ValueError:
                    idx = 0xFFFF
                appliers.append(("eq", lbl, idx, negate))
        else:
            code = int(val) if val.isdigit() else None
            appliers.append(("eq", lbl, code, negate))
    return appliers


def _apply_matchers(appliers, ch) -> np.ndarray | None:
    mask = None
    for kind, lbl, data, negate in appliers:
        arr = ch[lbl]
        if kind == "eq":
            m = (np.zeros(len(arr), bool) if data is None
                 else arr == arr.dtype.type(data))
        else:
            m = np.isin(arr, data)
        if negate:
            m = ~m
        mask = m if mask is None else (mask & m)
    return mask


def _labels_json_ids(table, lbl: str, op: str, val: str,
                     labels_col: str = "labels_json") -> np.ndarray:
    """Matching dictionary ids for a matcher over a json label set.
    (Negation is applied by the caller.)"""

    def get(s: str) -> str:
        try:
            return str(_json.loads(s or "{}").get(lbl, ""))
        except ValueError:
            return ""

    if op in ("=", "!="):
        pred = lambda s: get(s) == val  # noqa: E731
    else:
        rx = _compile(val)
        pred = lambda s: rx.fullmatch(get(s)) is not None  # noqa: E731
    return table.dicts[labels_col].match_ids(pred)


@dataclass
class RawSeries:
    """One series' raw samples: sorted times (s) and float values."""
    labels: dict
    t: np.ndarray
    v: np.ndarray
    counter: bool  # cumulative counter vs per-interval delta samples


def fetch_raw(db: Database, vs: VectorSelector, lo_s: float,
              hi_s: float) -> list[RawSeries]:
    """All samples in [lo_s, hi_s] for the selector, split into series by
    the full tag set (series identity is always the full tag set; any
    grouping happens later across evaluated series)."""
    # cluster federation hook: a federated db-shim intercepts selector
    # materialization here (BEFORE local metric resolution — a remote
    # shard may know a metric this node has never seen) and hands back
    # local + remote series merged by label set. The whole PromQL AST
    # then evaluates at the coordinator, so federated results are EXACT
    # for every function (Thanos-style raw-selector fan-out).
    hook = getattr(db, "promql_fetch_raw", None)
    if hook is not None:
        return hook(vs, lo_s, hi_s)
    table, col, tags, pre_filters, labels_col = _resolve_metric(db, vs.metric)
    appliers = _compile_matchers(table, vs.matchers, labels_col)
    # remote-write clients send CUMULATIVE counters (standard Prometheus),
    # dfstats self-telemetry snapshots cumulative process counters, and the
    # Telegraf fields people rate() (net/disk totals) are cumulative too;
    # internal flow_metrics tables hold per-interval DELTA samples.
    counter_mode = table.name in ("prometheus.samples",
                                  "deepflow_system.deepflow_system",
                                  "ext_metrics.metrics")
    chunks = table.snapshot()
    times, values, tag_arrays = [], [], {t: [] for t in tags}
    for ch in chunks:
        if not ch or not len(ch["time"]):
            continue
        t = ch["time"].astype(np.int64)
        # schema convention (same as engine._col_val): u64 time columns are
        # nanoseconds, u32 are epoch seconds
        if table.columns["time"].kind == "u64":
            t = t // 1_000_000_000
        mask = (t >= lo_s) & (t <= hi_s)
        for pf_col, pf_code in (pre_filters or []):
            mask &= ch[pf_col] == pf_code
        m = _apply_matchers(appliers, ch)
        if m is not None:
            mask &= m
        idx = np.flatnonzero(mask)
        if not len(idx):
            continue
        times.append(t[idx])
        values.append(ch[col][idx].astype(np.float64))
        for lbl in tags:
            tag_arrays[lbl].append(ch[lbl][idx])
    if not times:
        return []
    t_all = np.concatenate(times)
    v_all = np.concatenate(values)
    tag_all = {lbl: np.concatenate(tag_arrays[lbl]) for lbl in tags}

    group_labels = [g for g in tags if g in tag_all]
    key = np.zeros(len(t_all), dtype=np.int64)
    for lbl in group_labels:
        _, inv = np.unique(tag_all[lbl], return_inverse=True)
        # re-densify after every fold: key stays < n_rows, so the product
        # is bounded by n_rows^2 and can't overflow int64 even with many
        # high-cardinality labels
        _, key = np.unique(key * (int(inv.max(initial=0)) + 1) + inv,
                           return_inverse=True)

    out = []
    for gk in np.unique(key):
        gmask = key == gk
        gt, gv = t_all[gmask], v_all[gmask]
        order = np.argsort(gt, kind="stable")
        gt, gv = gt[order], gv[order]
        labels = {"__name__": vs.metric}
        gi = np.flatnonzero(gmask)[0]
        for lbl in group_labels:
            spec = table.columns[lbl]
            raw = tag_all[lbl][gi]
            if lbl == labels_col and spec.kind == "str":
                try:
                    labels.update(_json.loads(
                        table.dicts[lbl].decode(int(raw)) or "{}"))
                except ValueError:
                    pass
            elif spec.kind == "str":
                labels[lbl] = table.dicts[lbl].decode(int(raw))
            elif spec.kind == "enum":
                labels[lbl] = spec.enum_values[int(raw)]
            else:
                labels[lbl] = str(int(raw))
        out.append(RawSeries(labels=labels, t=gt, v=gv,
                             counter=counter_mode))
    return out


# -- evaluation --------------------------------------------------------------

@dataclass
class Series:
    """An evaluated series: one value per step (NaN = no sample)."""
    labels: dict
    vals: np.ndarray


def _sig(labels: dict, matching: VectorMatching | None) -> tuple:
    if matching is None:
        keep = sorted(k for k in labels if k != "__name__")
    elif matching.on:
        keep = sorted(matching.labels)
    else:
        drop = set(matching.labels) | {"__name__"}
        keep = sorted(k for k in labels if k not in drop)
    return tuple((k, labels.get(k, "")) for k in keep)


def _drop_name(labels: dict) -> dict:
    return {k: v for k, v in labels.items() if k != "__name__"}


def _group_key(labels: dict, grouping: list[str], without: bool) -> tuple:
    """Aggregation group signature for by(...)/without(...)."""
    if without:
        drop = set(grouping) | {"__name__"}
        return tuple(sorted((k, v) for k, v in labels.items()
                            if k not in drop))
    return tuple((k, labels.get(k, "")) for k in grouping)


def _counter_rate(wt: np.ndarray, wv: np.ndarray, fn: str, range_s: float,
                  range_lo: float, range_hi: float) -> float | None:
    """Prometheus counter semantics over one series window: monotonic
    cumulative values with reset detection (a drop means the counter
    restarted at ~0, so the post-reset value IS the increase), and the
    upstream extrapolatedRate window-boundary extrapolation."""
    if len(wt) < 2:
        return None
    if fn == "irate":
        # dedup to distinct timestamps (remote-write retries re-send batches;
        # last value wins for a cumulative counter), then take the last pair
        uts = np.unique(wt)
        if len(uts) < 2:
            return None
        # last row at each of the two last distinct timestamps
        i_last = int(np.searchsorted(wt, uts[-1], side="right")) - 1
        i_prev = int(np.searchsorted(wt, uts[-2], side="right")) - 1
        dv = float(wv[i_last] - wv[i_prev])
        if dv < 0:  # reset between the two points
            dv = float(wv[i_last])
        dt = float(uts[-1] - uts[-2])
        return dv / dt
    diffs = np.diff(wv)
    # increase = sum of positive deltas; at a reset the post-reset value is
    # the delta (counter restarted from ~0)
    increase = float(np.where(diffs >= 0, diffs, wv[1:]).sum())
    # extrapolate to the window bounds (promql/functions.go extrapolatedRate):
    # extend by up to half the average sample spacing, or to the boundary if
    # it's closer than 1.1x spacing; never extrapolate past the counter's
    # implied zero crossing
    sampled = float(wt[-1] - wt[0])
    if sampled <= 0:
        return None
    avg_spacing = sampled / (len(wt) - 1)
    threshold = avg_spacing * 1.1
    to_start = float(wt[0]) - range_lo
    to_end = range_hi - float(wt[-1])
    if to_start >= threshold:
        to_start = avg_spacing / 2
    if increase > 0 and wv[0] >= 0:
        to_zero = sampled * (float(wv[0]) / increase)
        to_start = min(to_start, to_zero)
    if to_end >= threshold:
        to_end = avg_spacing / 2
    increase *= (sampled + to_start + to_end) / sampled
    if fn == "increase":
        return increase
    return increase / max(range_s, 1e-9)


def _delta_rate(wt: np.ndarray, wv: np.ndarray, fn: str,
                range_s: float) -> float | None:
    """Delta-sample semantics for the internal flow_metrics tables: each row
    already holds the increase over its interval."""
    if not len(wt):
        return None
    if fn == "irate":
        # instantaneous: the last two DISTINCT timestamps in the window,
        # with co-timestamped rows summed (a series can hold several rows
        # per second)
        uts, inv = np.unique(wt, return_inverse=True)
        if len(uts) < 2:
            return None
        sums = np.bincount(inv, weights=wv)
        dt = float(uts[-1] - uts[-2])
        return float(sums[-1]) / dt
    total = float(wv.sum())
    if fn == "rate":
        return total / max(range_s, 1e-9)
    return total  # increase


def _range_fn_value(fn: str, wt: np.ndarray, wv: np.ndarray, counter: bool,
                    range_s: float, lo: float, hi: float,
                    phi: float = 0.0, horizon: float = 0.0) -> float | None:
    """Apply a range function to one series' window (lo, hi]."""
    n = len(wt)
    if fn in ("rate", "irate", "increase"):
        if counter:
            return _counter_rate(wt, wv, fn, range_s, lo, hi)
        return _delta_rate(wt, wv, fn, range_s)
    if fn in ("absent_over_time", "present_over_time"):
        raise AssertionError("handled by caller")
    if n == 0:
        return None
    if fn == "avg_over_time":
        return float(wv.mean())
    if fn == "min_over_time":
        return float(wv.min())
    if fn == "max_over_time":
        return float(wv.max())
    if fn == "sum_over_time":
        return float(wv.sum())
    if fn == "count_over_time":
        return float(n)
    if fn == "last_over_time":
        return float(wv[-1])
    if fn == "stddev_over_time":
        return float(wv.std())
    if fn == "stdvar_over_time":
        return float(wv.var())
    if fn == "quantile_over_time":
        return float(np.quantile(wv, min(max(phi, 0.0), 1.0)))
    if fn == "changes":
        return float(np.count_nonzero(np.diff(wv))) if n > 1 else 0.0
    if fn == "resets":
        return float(np.count_nonzero(np.diff(wv) < 0)) if n > 1 else 0.0
    if fn == "idelta":
        uts = np.unique(wt)
        if len(uts) < 2:
            return None
        i_last = int(np.searchsorted(wt, uts[-1], side="right")) - 1
        i_prev = int(np.searchsorted(wt, uts[-2], side="right")) - 1
        return float(wv[i_last] - wv[i_prev])
    if fn in ("delta", "deriv", "predict_linear"):
        if n < 2:
            return None
        sampled = float(wt[-1] - wt[0])
        if sampled <= 0:
            return None
        if fn == "delta":
            # gauge delta with the same boundary extrapolation as rate
            d = float(wv[-1] - wv[0])
            avg_spacing = sampled / (n - 1)
            threshold = avg_spacing * 1.1
            to_start = float(wt[0]) - lo
            to_end = hi - float(wt[-1])
            if to_start >= threshold:
                to_start = avg_spacing / 2
            if to_end >= threshold:
                to_end = avg_spacing / 2
            return d * (sampled + to_start + to_end) / sampled
        # least-squares slope (upstream uses simple linear regression
        # anchored at the window's first timestamp for stability)
        x = (wt - wt[0]).astype(np.float64)
        xm, ym = x.mean(), wv.mean()
        denom = float(((x - xm) ** 2).sum())
        if denom == 0:
            return None
        slope = float(((x - xm) * (wv - ym)).sum()) / denom
        if fn == "deriv":
            return slope
        # predict_linear: value at hi + horizon
        intercept = ym - slope * xm
        return intercept + slope * (hi - float(wt[0]) + horizon)
    raise PromqlError(f"unsupported range function {fn}()")


class _Evaluator:
    def __init__(self, db: Database, steps: np.ndarray,
                 default_res_s: float = 15.0):
        self.db = db
        self.steps = steps.astype(np.float64)
        self.default_res_s = default_res_s

    # -- selector eval -----------------------------------------------------

    def instant_vector(self, vs: VectorSelector) -> list[Series]:
        off = vs.offset_s
        lo = float(self.steps[0]) - off - _LOOKBACK_S
        hi = float(self.steps[-1]) - off
        out = []
        for rs in fetch_raw(self.db, vs, lo, hi):
            q = self.steps - off
            idx = np.searchsorted(rs.t, q, side="right") - 1
            valid = idx >= 0
            safe = np.where(valid, idx, 0)
            age = q - rs.t[safe]
            valid &= age <= _LOOKBACK_S
            vals = np.where(valid, rs.v[safe], np.nan)
            if np.isnan(vals).all():
                continue
            out.append(Series(labels=rs.labels, vals=vals))
        return out

    def range_series(self, node) -> tuple[list[RawSeries], float, float]:
        """-> (raw series, range_s, offset_s) for a matrix selector or
        subquery argument of a range function."""
        if isinstance(node, MatrixSelector):
            off = node.vs.offset_s
            lo = float(self.steps[0]) - off - node.range_s
            hi = float(self.steps[-1]) - off
            return fetch_raw(self.db, node.vs, lo, hi), node.range_s, off
        if isinstance(node, Subquery):
            return (self.eval_subquery(node), node.range_s, node.offset_s)
        raise PromqlError("expected a range expression (selector[d] or "
                          "subquery[d:s])")

    def eval_subquery(self, sq: Subquery) -> list[RawSeries]:
        res = sq.step_s or self.default_res_s
        off = sq.offset_s
        lo = float(self.steps[0]) - off - sq.range_s
        hi = float(self.steps[-1]) - off
        # subquery steps align to absolute multiples of the resolution
        first = math.ceil(lo / res) * res
        sub_steps = np.arange(first, hi + res / 2, res)
        if not len(sub_steps):
            return []
        sub = _Evaluator(self.db, sub_steps, self.default_res_s)
        vec = sub.eval_vector(sq.expr, "subquery")
        out = []
        for s in vec:
            keep = ~np.isnan(s.vals)
            if not keep.any():
                continue
            # subquery output samples are treated as cumulative by the
            # counter-aware range functions, matching upstream rate() over
            # subqueries
            out.append(RawSeries(labels=s.labels, t=sub_steps[keep],
                                 v=s.vals[keep], counter=True))
        return out

    # -- generic eval ------------------------------------------------------

    def eval(self, node):
        """-> Series list (vector) or np.ndarray (scalar-per-step)."""
        if isinstance(node, Num):
            return np.full(len(self.steps), node.value)
        if isinstance(node, Str):
            return node
        if isinstance(node, VectorSelector):
            return self.instant_vector(node)
        if isinstance(node, (MatrixSelector, Subquery)):
            raise PromqlError("range expression must be wrapped in a "
                              "range function like rate()")
        if isinstance(node, Unary):
            val = self.eval(node.expr)
            if isinstance(val, Str):
                raise PromqlError("cannot negate a string")
            if isinstance(val, np.ndarray):
                return -val
            return [Series(labels=_drop_name(s.labels), vals=-s.vals)
                    for s in val]
        if isinstance(node, Call):
            return self.eval_call(node)
        if isinstance(node, Agg):
            return self.eval_agg(node)
        if isinstance(node, BinOp):
            return self.eval_binop(node)
        raise PromqlError(f"cannot evaluate {type(node).__name__}")

    def eval_vector(self, node, ctx: str) -> list[Series]:
        v = self.eval(node)
        if isinstance(v, np.ndarray):
            raise PromqlError(f"{ctx} expects an instant vector, got scalar")
        if isinstance(v, Str):
            raise PromqlError(f"{ctx} expects an instant vector, got string")
        return v

    def eval_scalar(self, node, ctx: str) -> np.ndarray:
        v = self.eval(node)
        if not isinstance(v, np.ndarray):
            raise PromqlError(f"{ctx} expects a scalar")
        return v

    # -- functions ---------------------------------------------------------

    def eval_call(self, node: Call):
        fn = node.fn
        if fn in _RANGE_FNS:
            return self.eval_range_fn(node)
        if fn == "time":
            return self.steps.copy()
        if fn == "scalar":
            vec = self.eval_vector(node.args[0], "scalar()")
            out = np.full(len(self.steps), np.nan)
            if len(vec) == 1:
                out = vec[0].vals.copy()
            return out
        if fn == "vector":
            s = self.eval_scalar(node.args[0], "vector()")
            return [Series(labels={}, vals=s)]
        if fn == "absent":
            vec = self.eval(node.args[0]) if not isinstance(
                node.args[0], VectorSelector) else None
            labels = {}
            if isinstance(node.args[0], VectorSelector):
                try:
                    vec = self.instant_vector(node.args[0])
                except UnknownMetricError:
                    vec = []  # unknown metric is definitionally absent
                labels = {lbl: val for lbl, op, val
                          in node.args[0].matchers if op == "="}
            if isinstance(vec, (np.ndarray, Str)):
                raise PromqlError("absent() expects an instant vector")
            present = np.zeros(len(self.steps), dtype=bool)
            for s in (vec or []):
                present |= ~np.isnan(s.vals)
            vals = np.where(present, np.nan, 1.0)
            if np.isnan(vals).all():
                return []
            return [Series(labels=labels, vals=vals)]
        if fn in _MATH_FNS:
            vec = self.eval(node.args[0])
            op = _MATH_FNS[fn]
            with np.errstate(all="ignore"):
                if isinstance(vec, np.ndarray):
                    return op(vec)
                return [Series(labels=_drop_name(s.labels),
                               vals=op(s.vals)) for s in vec]
        if fn == "round":
            vec = self.eval_vector(node.args[0], "round()")
            to = np.ones(len(self.steps))
            if len(node.args) > 1:
                to = self.eval_scalar(node.args[1], "round()")
            if np.any(to <= 0):
                raise PromqlError("round() nearest must be positive")
            # Prometheus rounds half toward +Inf, not half-to-even;
            # `to` applies per step (it can be a varying scalar expr)
            return [Series(labels=_drop_name(s.labels),
                           vals=np.floor(s.vals / to + 0.5) * to)
                    for s in vec]
        if fn in ("clamp", "clamp_min", "clamp_max"):
            vec = self.eval_vector(node.args[0], fn)
            if fn == "clamp":
                lo = self.eval_scalar(node.args[1], fn)
                hi = self.eval_scalar(node.args[2], fn)
                return [Series(labels=_drop_name(s.labels),
                               vals=np.clip(s.vals, lo, hi)) for s in vec]
            bound = self.eval_scalar(node.args[1], fn)
            f = np.maximum if fn == "clamp_min" else np.minimum
            return [Series(labels=_drop_name(s.labels),
                           vals=f(s.vals, bound)) for s in vec]
        if fn == "timestamp":
            vec = self.eval_vector(node.args[0], fn)
            return [Series(labels=_drop_name(s.labels),
                           vals=np.where(np.isnan(s.vals), np.nan,
                                         self.steps)) for s in vec]
        if fn == "histogram_quantile":
            phi_arr = self.eval_scalar(node.args[0], fn)
            vec = self.eval_vector(node.args[1], fn)
            return self._histogram_quantile(phi_arr, vec)
        if fn == "label_replace":
            vec = self.eval_vector(node.args[0], fn)
            dst, repl, src, regex = (self._str_arg(a) for a in node.args[1:5])
            rx = _compile(regex)
            out = []
            for s in vec:
                labels = dict(s.labels)
                m = rx.fullmatch(labels.get(src, ""))
                if m:
                    val = m.expand(re.sub(r"\$(\d+)", r"\\\1", repl))
                    if val:
                        labels[dst] = val
                    else:
                        labels.pop(dst, None)
                out.append(Series(labels=labels, vals=s.vals))
            return out
        if fn == "label_join":
            vec = self.eval_vector(node.args[0], fn)
            dst = self._str_arg(node.args[1])
            sep = self._str_arg(node.args[2])
            srcs = [self._str_arg(a) for a in node.args[3:]]
            out = []
            for s in vec:
                labels = dict(s.labels)
                labels[dst] = sep.join(labels.get(k, "") for k in srcs)
                out.append(Series(labels=labels, vals=s.vals))
            return out
        if fn in ("sort", "sort_desc"):
            vec = self.eval_vector(node.args[0], fn)
            def last_val(s):
                ok = s.vals[~np.isnan(s.vals)]
                return float(ok[-1]) if len(ok) else -math.inf
            return sorted(vec, key=last_val, reverse=(fn == "sort_desc"))
        raise PromqlError(f"unsupported function {fn}()")

    def _str_arg(self, node) -> str:
        if not isinstance(node, Str):
            raise PromqlError("expected a string literal argument")
        return node.value

    def eval_range_fn(self, node: Call) -> list[Series]:
        fn = node.fn
        phi_arr = None
        horizon_arr = None
        if fn == "quantile_over_time":
            phi_arr = self.eval_scalar(node.args[0], fn)
            range_arg = node.args[1]
        elif fn == "predict_linear":
            horizon_arr = self.eval_scalar(node.args[1], fn)
            range_arg = node.args[0]
        else:
            if len(node.args) != 1:
                raise PromqlError(f"{fn}() takes one range argument")
            range_arg = node.args[0]
        raw, range_s, off = self.range_series(range_arg)
        if fn in ("rate", "irate", "increase") and isinstance(
                range_arg, MatrixSelector) and range_s <= 0:
            raise PromqlError(f"{fn}() needs a [range]")
        steps = self.steps
        if fn == "absent_over_time":
            present = np.zeros(len(steps), dtype=bool)
            for rs in raw:
                for i, ts in enumerate(steps):
                    hi = float(ts) - off
                    lo = hi - range_s
                    i0 = int(np.searchsorted(rs.t, lo, side="right"))
                    i1 = int(np.searchsorted(rs.t, hi, side="right"))
                    if i1 > i0:
                        present[i] = True
            vals = np.where(present, np.nan, 1.0)
            if np.isnan(vals).all():
                return []
            labels = {}
            if isinstance(range_arg, MatrixSelector):
                labels = {lbl: val for lbl, op, val
                          in range_arg.vs.matchers if op == "="}
            return [Series(labels=labels, vals=vals)]
        out = []
        for rs in raw:
            vals = np.full(len(steps), np.nan)
            for i, ts in enumerate(steps):
                hi = float(ts) - off
                lo = hi - range_s
                i0 = int(np.searchsorted(rs.t, lo, side="right"))
                i1 = int(np.searchsorted(rs.t, hi, side="right"))
                if fn == "present_over_time":
                    if i1 > i0:
                        vals[i] = 1.0
                    continue
                phi = (float(phi_arr[i]) if phi_arr is not None else 0.0)
                # the horizon scalar applies per step (it can vary)
                horizon = (float(horizon_arr[i])
                           if horizon_arr is not None else 0.0)
                v = _range_fn_value(fn, rs.t[i0:i1], rs.v[i0:i1], rs.counter,
                                    range_s, lo, hi, phi=phi,
                                    horizon=horizon)
                if v is not None:
                    vals[i] = v
            if np.isnan(vals).all():
                continue
            out.append(Series(labels=_drop_name(rs.labels), vals=vals))
        return out

    def _histogram_quantile(self, phi_arr: np.ndarray,
                            vec: list[Series]) -> list[Series]:
        groups: dict[tuple, list[tuple[float, Series]]] = {}
        for s in vec:
            le = s.labels.get("le")
            if le is None:
                continue
            try:
                bound = float(le)
            except ValueError:
                continue
            key = tuple(sorted((k, v) for k, v in s.labels.items()
                               if k not in ("le", "__name__")))
            groups.setdefault(key, []).append((bound, s))
        out = []
        for key, buckets in groups.items():
            buckets.sort(key=lambda bs: bs[0])
            bounds = np.array([b for b, _ in buckets])
            mat = np.vstack([s.vals for _, s in buckets])
            vals = np.full(len(self.steps), np.nan)
            for i in range(len(self.steps)):
                col = mat[:, i]
                ok = ~np.isnan(col)
                if not ok.any():
                    continue
                b = bounds[ok]
                c = np.maximum.accumulate(col[ok])  # enforce monotonicity
                if len(b) < 2 or not math.isinf(b[-1]):
                    continue  # need an +Inf bucket to anchor the total
                total = c[-1]
                if total <= 0:
                    continue
                phi = float(phi_arr[i])
                if phi < 0:
                    vals[i] = -math.inf
                    continue
                if phi > 1:
                    vals[i] = math.inf
                    continue
                rank = phi * total
                j = int(np.searchsorted(c, rank, side="left"))
                j = min(j, len(b) - 1)
                if j == len(b) - 1:  # falls in the +Inf bucket
                    vals[i] = float(b[-2])
                    continue
                lo_bound = float(b[j - 1]) if j > 0 else 0.0
                if j == 0 and b[0] <= 0:
                    lo_bound = float(b[0])
                lo_count = float(c[j - 1]) if j > 0 else 0.0
                span = float(c[j]) - lo_count
                if span <= 0:
                    vals[i] = float(b[j])
                    continue
                vals[i] = lo_bound + (float(b[j]) - lo_bound) * (
                    (rank - lo_count) / span)
            if np.isnan(vals).all():
                continue
            out.append(Series(labels=dict(key), vals=vals))
        return out

    # -- aggregation -------------------------------------------------------

    def eval_agg(self, node: Agg) -> list[Series]:
        vec = self.eval_vector(node.expr, node.op)
        if node.op == "count_values":
            return self._count_values(node, vec)
        param = None
        if node.param is not None:
            param = self.eval_scalar(node.param, node.op)

        groups: dict[tuple, list[Series]] = {}
        for s in vec:
            groups.setdefault(
                _group_key(s.labels, node.grouping, node.without),
                []).append(s)
        out = []
        for key, members in groups.items():
            mat = np.vstack([s.vals for s in members])
            valid = ~np.isnan(mat)
            any_valid = valid.any(axis=0)
            with np.errstate(all="ignore"):
                if node.op == "sum":
                    vals = np.nansum(mat, axis=0)
                elif node.op == "avg":
                    vals = np.nanmean(mat, axis=0)
                elif node.op == "min":
                    vals = np.nanmin(
                        np.where(valid, mat, np.inf), axis=0)
                elif node.op == "max":
                    vals = np.nanmax(
                        np.where(valid, mat, -np.inf), axis=0)
                elif node.op == "count":
                    vals = valid.sum(axis=0).astype(np.float64)
                elif node.op == "group":
                    vals = np.ones(mat.shape[1])
                elif node.op == "stddev":
                    vals = np.nanstd(mat, axis=0)
                elif node.op == "stdvar":
                    vals = np.nanvar(mat, axis=0)
                elif node.op == "quantile":
                    phi = np.clip(param, 0.0, 1.0)
                    vals = np.full(mat.shape[1], np.nan)
                    for i in range(mat.shape[1]):
                        col = mat[:, i][valid[:, i]]
                        if len(col):
                            vals[i] = float(np.quantile(col, float(phi[i])))
                elif node.op in ("topk", "bottomk"):
                    # per-step selection: members keep their own labels
                    k_arr = param
                    keep = np.zeros_like(mat, dtype=bool)
                    sign = -1.0 if node.op == "topk" else 1.0
                    for i in range(mat.shape[1]):
                        k = int(k_arr[i]) if not math.isnan(k_arr[i]) else 0
                        if k <= 0:
                            continue
                        col = np.where(valid[:, i], sign * mat[:, i], np.inf)
                        order = np.argsort(col, kind="stable")
                        chosen = [j for j in order[:k] if valid[j, i]]
                        keep[chosen, i] = True
                    for j, s in enumerate(members):
                        vals_j = np.where(keep[j], mat[j], np.nan)
                        if not np.isnan(vals_j).all():
                            # topk/bottomk keep the member's own labels
                            out.append(Series(labels=dict(s.labels),
                                              vals=vals_j))
                    continue
                else:
                    raise PromqlError(f"unsupported aggregate {node.op}")
            vals = np.where(any_valid, vals, np.nan)
            if np.isnan(vals).all():
                continue
            out.append(Series(labels=dict(key), vals=vals))
        return out

    def _count_values(self, node: Agg, vec: list[Series]) -> list[Series]:
        if not isinstance(node.param, Str):
            raise PromqlError("count_values() needs a string label")
        dst = node.param.value
        counts: dict[tuple, np.ndarray] = {}
        for s in vec:
            base = _group_key(s.labels, node.grouping, node.without)
            for i, v in enumerate(s.vals):
                if math.isnan(v):
                    continue
                sval = (_fmt_num(v) if not math.isfinite(v)
                        else repr(v) if v != int(v) else str(int(v)))
                key = base + ((dst, sval),)
                if key not in counts:
                    counts[key] = np.full(len(self.steps), np.nan)
                cur = counts[key][i]
                counts[key][i] = 1.0 if math.isnan(cur) else cur + 1.0
        return [Series(labels=dict(key), vals=vals)
                for key, vals in counts.items()]

    # -- binary operators --------------------------------------------------

    def eval_binop(self, node: BinOp):
        lhs = self.eval(node.lhs)
        rhs = self.eval(node.rhs)
        l_scalar = isinstance(lhs, np.ndarray)
        r_scalar = isinstance(rhs, np.ndarray)
        op = node.op
        if op in _SET_OPS:
            if l_scalar or r_scalar:
                raise PromqlError(f"{op} requires vectors on both sides")
            return self._set_op(op, lhs, rhs, node.matching)
        if l_scalar and r_scalar:
            if op in _CMP_OPS and not node.bool_mod:
                raise PromqlError(
                    "comparison between scalars needs the bool modifier")
            with np.errstate(all="ignore"):
                return self._apply_op(op, lhs, rhs, bool_mod=True)
        if l_scalar or r_scalar:
            vec, sc, flip = ((rhs, lhs, True) if l_scalar
                             else (lhs, rhs, False))
            out = []
            for s in vec:
                a, b = (sc, s.vals) if flip else (s.vals, sc)
                with np.errstate(all="ignore"):
                    vals = self._apply_op(op, a, b, bool_mod=node.bool_mod)
                if op in _CMP_OPS and not node.bool_mod:
                    # filter: keep the vector's own value where true
                    vals = np.where(np.isnan(vals), np.nan, s.vals)
                if np.isnan(vals).all():
                    continue
                labels = (_drop_name(s.labels)
                          if (op not in _CMP_OPS or node.bool_mod)
                          else dict(s.labels))
                out.append(Series(labels=labels, vals=vals))
            return out
        return self._vector_binop(node, lhs, rhs)

    def _apply_op(self, op: str, a, b, bool_mod: bool) -> np.ndarray:
        if op == "+":
            return a + b
        if op == "-":
            return a - b
        if op == "*":
            return a * b
        if op == "/":
            return np.where(b == 0, np.where(
                np.isnan(np.asarray(a, dtype=float)), np.nan,
                np.sign(a) * np.inf), a / np.where(b == 0, 1, b))
        if op == "%":
            return np.where(b == 0, np.nan, np.fmod(a, np.where(b == 0, 1, b)))
        if op == "^":
            return np.power(a, b)
        cmp = {"==": np.equal, "!=": np.not_equal, ">": np.greater,
               "<": np.less, ">=": np.greater_equal,
               "<=": np.less_equal}[op](a, b)
        # NaN on either side -> no result
        nan = np.isnan(np.asarray(a, dtype=float)) | np.isnan(
            np.asarray(b, dtype=float))
        if bool_mod:
            return np.where(nan, np.nan, cmp.astype(np.float64))
        return np.where(nan | ~cmp, np.nan, 1.0)

    def _set_op(self, op: str, lhs: list[Series], rhs: list[Series],
                matching: VectorMatching | None) -> list[Series]:
        # per-step presence matters: `and` keeps lhs points whose signature
        # has a present rhs point at that step
        rsig: dict[tuple, np.ndarray] = {}
        for s in rhs:
            sig = _sig(s.labels, matching)
            present = ~np.isnan(s.vals)
            rsig[sig] = rsig.get(sig, np.zeros(len(self.steps),
                                               dtype=bool)) | present
        out = []
        if op in ("and", "unless"):
            for s in lhs:
                mask = rsig.get(_sig(s.labels, matching),
                                np.zeros(len(self.steps), dtype=bool))
                if op == "unless":
                    mask = ~mask
                vals = np.where(mask, s.vals, np.nan)
                if not np.isnan(vals).all():
                    out.append(Series(labels=s.labels, vals=vals))
            return out
        # or: all of lhs, plus rhs points whose signature has no lhs point
        lsig: dict[tuple, np.ndarray] = {}
        for s in lhs:
            sig = _sig(s.labels, matching)
            present = ~np.isnan(s.vals)
            lsig[sig] = lsig.get(sig, np.zeros(len(self.steps),
                                               dtype=bool)) | present
            out.append(s)
        for s in rhs:
            lmask = lsig.get(_sig(s.labels, matching),
                             np.zeros(len(self.steps), dtype=bool))
            vals = np.where(lmask, np.nan, s.vals)
            if not np.isnan(vals).all():
                out.append(Series(labels=s.labels, vals=vals))
        return out

    def _vector_binop(self, node: BinOp, lhs: list[Series],
                      rhs: list[Series]) -> list[Series]:
        matching = node.matching or VectorMatching()
        card = matching.card
        # normalize to a uniform (many, one) walk; lhs_is_many records which
        # operand order a matched pair evaluates in
        if card == "one-to-many":
            many, one, lhs_is_many = rhs, lhs, False
        else:
            many, one, lhs_is_many = lhs, rhs, True
        one_by_sig: dict[tuple, Series] = {}
        for s in one:
            sig = _sig(s.labels, matching)
            if sig in one_by_sig:
                raise PromqlError(
                    "many-to-many matching: duplicate series on the "
                    f"{'left' if lhs_is_many else 'right'} side "
                    f"for signature {dict(sig)!r}")
            one_by_sig[sig] = s
        if card == "one-to-one":
            seen: set[tuple] = set()
            for s in many:
                sig = _sig(s.labels, matching)
                if sig in seen:
                    raise PromqlError(
                        "many-to-many matching: duplicate series on the "
                        f"left side for signature {dict(sig)!r}")
                seen.add(sig)
        out = []
        for s in many:
            other = one_by_sig.get(_sig(s.labels, matching))
            if other is None:
                continue
            a, b = ((s.vals, other.vals) if lhs_is_many
                    else (other.vals, s.vals))
            with np.errstate(all="ignore"):
                vals = self._apply_op(node.op, a, b,
                                      bool_mod=node.bool_mod)
            if node.op in _CMP_OPS and not node.bool_mod:
                # filter comparisons keep the LEFT operand's value
                vals = np.where(np.isnan(vals), np.nan, a)
            if np.isnan(vals).all():
                continue
            # result labels
            if card == "one-to-one":
                if node.op in _CMP_OPS and not node.bool_mod:
                    # filter keeps the lhs series labels as-is
                    labels = dict(s.labels)
                elif matching.on:
                    labels = dict(_sig(s.labels, matching))
                else:
                    # drop __name__ AND the ignored labels (upstream
                    # resultMetric deletes ignoring(...) labels)
                    drop = set(matching.labels) | {"__name__"}
                    labels = {k: v for k, v in s.labels.items()
                              if k not in drop}
            else:
                labels = _drop_name(dict(s.labels))
                for lbl in matching.include:
                    if lbl in other.labels:
                        labels[lbl] = other.labels[lbl]
                    else:
                        labels.pop(lbl, None)
            out.append(Series(labels=labels, vals=vals))
        return out


# -- public API --------------------------------------------------------------

def scope_to_org(node, org_id: int):
    """Enforce tenancy on a parsed query: append an org_id matcher to
    every vector selector (org_id is a universal-tag column on every
    table, so the numeric-eq matcher path applies it). Returns the same
    AST, mutated."""
    if isinstance(node, VectorSelector):
        node.matchers = [m for m in node.matchers if m[0] != "org_id"]
        node.matchers.append(("org_id", "=", str(int(org_id))))
        return node
    if isinstance(node, MatrixSelector):
        scope_to_org(node.vs, org_id)
        return node
    for f in getattr(node, "__dataclass_fields__", {}):
        v = getattr(node, f)
        if isinstance(v, list):
            for item in v:
                if hasattr(item, "__dataclass_fields__"):
                    scope_to_org(item, org_id)
        elif hasattr(v, "__dataclass_fields__"):
            scope_to_org(v, org_id)
    return node


def evaluate(db: Database, query, start_s: int, end_s: int,
             step_s: int = 15) -> list[dict]:
    """Range evaluation -> prometheus matrix result
    [{"metric": labels, "values": [(ts, value), ...]}, ...]."""
    if isinstance(query, str):
        query = parse(query)
    steps = np.arange(start_s, end_s + 1, step_s, dtype=np.int64)
    if not len(steps):
        return []
    ev = _Evaluator(db, steps, default_res_s=float(step_s))
    result = ev.eval(query)
    if isinstance(result, Str):
        raise PromqlError("query evaluates to a string, not a vector")
    if isinstance(result, np.ndarray):
        vals = [(int(t), _json_num(v)) for t, v in zip(steps, result)
                if not math.isnan(v)]
        return [{"metric": {}, "values": vals}] if vals else []
    out = []
    for s in result:
        vals = [(int(t), _json_num(v)) for t, v in zip(steps, s.vals)
                if not math.isnan(v)]
        if vals:
            out.append({"metric": s.labels, "values": vals})
    return out


def _json_num(v: float):
    """Finite floats stay numbers; +/-Inf must not reach json.dumps (it
    emits the invalid-JSON token Infinity), so they go out as the
    prometheus string spelling."""
    v = float(v)
    return v if math.isfinite(v) else _fmt_num(v)


def evaluate_instant(db: Database, query, time_s: int) -> dict:
    """Instant evaluation -> {"resultType": "vector"|"scalar", "result": ...}
    in the prometheus HTTP API shape."""
    if isinstance(query, str):
        query = parse(query)
    steps = np.asarray([time_s], dtype=np.int64)
    ev = _Evaluator(db, steps)
    result = ev.eval(query)
    if isinstance(result, Str):
        return {"resultType": "string", "result": [time_s, result.value]}
    if isinstance(result, np.ndarray):
        v = float(result[0])
        return {"resultType": "scalar", "result": [time_s, _fmt_num(v)]}
    vec = []
    for s in result:
        v = float(s.vals[0])
        if math.isnan(v):
            continue
        vec.append({"metric": s.labels, "value": [time_s, _fmt_num(v)]})
    return {"resultType": "vector", "result": vec}


def _fmt_num(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    return repr(v)


# -- metadata API (Grafana variable queries) ---------------------------------

_JSON_LABEL_SCAN_CAP = 50_000  # labels_json dict entries parsed per table


def _codes_in_range(table, col: str, lo_s: float, hi_s: float) -> set[int]:
    """Distinct dictionary/enum codes of `col` among rows in the time
    range. Chunk-scanned, NOT dictionary-snapshotted: dictionaries retain
    every string ever encoded, so a snapshot would resurrect TTL-trimmed
    values and ignore the range."""
    codes: set[int] = set()
    ns = table.columns["time"].kind == "u64"
    for ch in table.snapshot():
        if not ch or not len(ch.get(col, ())):
            continue
        t = ch["time"].astype(np.int64)
        if ns:
            t = t // 1_000_000_000
        mask = (t >= lo_s) & (t <= hi_s)
        if not mask.any():
            continue
        codes.update(int(c) for c in np.unique(ch[col][mask]))
    return codes


def metric_names(db: Database, start_s: float = 0,
                 end_s: float = 1 << 62) -> list[str]:
    """Every queryable metric name (the /prom/api/v1/label/__name__/values
    answer): <family>_<meter> for the flow tables (schema-derived),
    observed deepflow_system metric/value pairs, and remote-write names —
    the observed sets chunk-scanned within [start_s, end_s] so
    retention-trimmed metrics don't linger."""
    out: set[str] = set()
    for prefix, (tname, _tags) in _FAMILIES.items():
        try:
            table = db.table(tname)
        except KeyError:
            continue
        for col, spec in table.columns.items():
            if spec.kind == "u64":  # meters are u64; tags are str/enum/ints
                out.add(prefix + col)
    for prefix, tname in _NARROW_TABLES:
        try:
            table = db.table(tname)
            pairs: set[tuple[int, int]] = set()
            for ch in table.snapshot():
                if not ch or not len(ch.get("metric_name", ())):
                    continue
                t = ch["time"].astype(np.int64) // 1_000_000_000
                mask = (t >= start_s) & (t <= end_s)
                if not mask.any():
                    continue
                for mi, vi in zip(*np.unique(np.stack(
                        [ch["metric_name"][mask], ch["value_name"][mask]]),
                        axis=1)):
                    pairs.add((int(mi), int(vi)))
            mdict = table.dicts["metric_name"]
            vdict = table.dicts["value_name"]
            for mi, vi in pairs:
                mn, vn = mdict.decode(mi), vdict.decode(vi)
                if mn and vn:
                    out.add(f"{prefix}{_mangle(mn)}_{_mangle(vn)}")
        except (KeyError, IndexError):
            pass
    try:
        table = db.table("prometheus.samples")
        d = table.dicts["metric_name"]
        for c in _codes_in_range(table, "metric_name", start_s, end_s):
            try:
                name = d.decode(c)
            except IndexError:
                continue
            if name:
                out.add(name)
    except KeyError:
        pass
    return sorted(out)


def series(db: Database, matches: list[str], start_s: int,
           end_s: int) -> list[dict]:
    """GET /prom/api/v1/series: label sets of series matching any of the
    match[] selectors in the time range."""
    seen: set[tuple] = set()
    out: list[dict] = []
    for m in matches:
        ast = parse(m)
        if not isinstance(ast, VectorSelector):
            raise PromqlError("series match[] must be a plain selector")
        try:
            raw = fetch_raw(db, ast, start_s, end_s)
        except UnknownMetricError:
            continue  # never-ingested metric matches nothing; any OTHER
            # PromqlError (bad regex, unknown label) propagates as 400
        for rs in raw:
            key = tuple(sorted(rs.labels.items()))
            if key not in seen:
                seen.add(key)
                out.append(rs.labels)
    return out


def _all_label_names(db: Database, start_s: int, end_s: int) -> set[str]:
    names = {"__name__"}
    for _prefix, (tname, tags) in _FAMILIES.items():
        try:
            db.table(tname)
        except KeyError:
            continue
        names.update(tags)
    for tname, json_col in (("prometheus.samples", "labels_json"),
                            ("deepflow_system.deepflow_system", "tag_json")):
        try:
            table = db.table(tname)
        except KeyError:
            continue
        d = table.dicts[json_col]
        for i, code in enumerate(_codes_in_range(table, json_col,
                                                 start_s, end_s)):
            if i > _JSON_LABEL_SCAN_CAP:
                break
            try:
                names.update(_json.loads(d.decode(code) or "{}").keys())
            except (ValueError, IndexError):
                pass
    names.update(("host", "agent_id"))
    return names


def label_names(db: Database, matches: list[str], start_s: int,
                end_s: int) -> list[str]:
    """GET /prom/api/v1/labels."""
    if matches:
        names: set[str] = set()
        for s in series(db, matches, start_s, end_s):
            names.update(s.keys())
        return sorted(names)
    return sorted(_all_label_names(db, start_s, end_s))


def label_values(db: Database, label: str, matches: list[str],
                 start_s: int, end_s: int) -> list[str]:
    """GET /prom/api/v1/label/<name>/values. Values come from rows in the
    time range (chunk scan), not dictionary snapshots — retention-trimmed
    values must not haunt Grafana dropdowns."""
    if label == "__name__":
        if matches:
            return sorted({s.get("__name__", "")
                           for s in series(db, matches, start_s, end_s)}
                          - {""})
        return metric_names(db, start_s, end_s)
    if matches:
        return sorted({s[label] for s in series(db, matches, start_s, end_s)
                       if label in s})
    values: set[str] = set()
    for _prefix, (tname, tags) in _FAMILIES.items():
        if label not in tags:
            continue
        try:
            table = db.table(tname)
        except KeyError:
            continue
        spec = table.columns.get(label)
        if spec is None:
            continue
        codes = _codes_in_range(table, label, start_s, end_s)
        if spec.kind == "str":
            d = table.dicts[label]
            for c in codes:
                try:
                    s = d.decode(c)
                except IndexError:
                    continue
                if s:
                    values.add(s)
        elif spec.kind == "enum":
            for c in codes:
                if 0 <= c < len(spec.enum_values) and spec.enum_values[c]:
                    values.add(spec.enum_values[c])
        else:
            # numeric tags (server_port, agent_id, ...) render the same
            # way series() does: str(int)
            values.update(str(c) for c in codes)
    for tname, json_col in (("prometheus.samples", "labels_json"),
                            ("deepflow_system.deepflow_system", "tag_json")):
        try:
            table = db.table(tname)
        except KeyError:
            continue
        if label in table.columns and table.columns[label].kind == "str":
            d = table.dicts[label]
            for c in _codes_in_range(table, label, start_s, end_s):
                try:
                    s = d.decode(c)
                except IndexError:
                    continue
                if s:
                    values.add(s)
            continue
        d = table.dicts[json_col]
        for i, code in enumerate(_codes_in_range(table, json_col,
                                                 start_s, end_s)):
            if i > _JSON_LABEL_SCAN_CAP:
                break
            try:
                v = _json.loads(d.decode(code) or "{}").get(label)
            except (ValueError, IndexError):
                continue
            if v is not None and str(v):
                values.add(str(v))
    return sorted(values)
