"""PromQL subset over the metric tables.

Reference analog: server/querier/app/prometheus (full upstream promql engine
over DeepFlow storage). Embedded subset with the shapes Grafana panels
actually send:

    metric
    metric{label="v", label2!="w"}
    rate(metric[5m])            (also irate, increase)
    sum(expr) / avg / min / max / count
    sum by (label, ...) (expr)
    expr / expr  (scalar arithmetic between aggregates is NOT supported;
                  binary ops are vector-scalar only: expr * 8, expr / 60)

Metric naming: <family>_<column>, e.g. flow_metrics_network_byte_tx or
flow_metrics_application_request. Labels are the table's tag columns.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

import numpy as np

from deepflow_tpu.store.db import Database

_DUR_RE = re.compile(r"^(\d+)(ms|s|m|h|d)$")
_DUR_S = {"ms": 0.001, "s": 1, "m": 60, "h": 3600, "d": 86400}

_AGGS = ("sum", "avg", "min", "max", "count")
_RATES = ("rate", "irate", "increase")

# metric prefix -> (table, tag label columns)
_NETWORK_TAGS = ["ip_src", "ip_dst", "server_port", "protocol", "host",
                 "pod_name", "tpu_pod", "slice_id", "agent_id"]
_APP_TAGS = ["ip_src", "ip_dst", "server_port", "l7_protocol", "app_service",
             "host", "pod_name", "tpu_pod", "slice_id", "agent_id"]

_FAMILIES = {
    "flow_metrics_network_": ("flow_metrics.network.1s", _NETWORK_TAGS),
    "flow_metrics_application_": ("flow_metrics.application.1s", _APP_TAGS),
}


class PromqlError(Exception):
    pass


def parse_duration_s(s: str) -> float:
    m = _DUR_RE.match(s)
    if not m:
        raise PromqlError(f"bad duration {s!r}")
    return int(m.group(1)) * _DUR_S[m.group(2)]


@dataclass
class Selector:
    metric: str
    matchers: list = field(default_factory=list)  # (label, op, value)
    range_s: float = 0.0


@dataclass
class Query:
    selector: Selector
    rate_fn: str = ""          # rate | irate | increase | ""
    agg: str = ""              # sum | avg | ...
    by: list = field(default_factory=list)
    scalar_op: str = ""        # * / + -
    scalar: float = 0.0


_TOKEN = re.compile(r"""
    (?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)
  | (?P<lbrace>\{) | (?P<rbrace>\})
  | (?P<lparen>\() | (?P<rparen>\))
  | (?P<lbrack>\[) | (?P<rbrack>\])
  | (?P<str>"(?:[^"\\]|\\.)*")
  | (?P<num>\d+\.\d+|\d+)
  | (?P<op>!=|=~|!~|=|,|\*|/|\+|-)
  | (?P<ws>\s+)
""", re.VERBOSE)


def _tokens(q: str):
    out, i = [], 0
    while i < len(q):
        m = _TOKEN.match(q, i)
        if not m:
            raise PromqlError(f"bad token at {i}: {q[i:i+10]!r}")
        i = m.end()
        if m.lastgroup != "ws":
            out.append((m.lastgroup, m.group()))
    return out


def parse(q: str) -> Query:
    toks = _tokens(q)
    pos = [0]

    def peek():
        return toks[pos[0]] if pos[0] < len(toks) else ("eof", "")

    def next_():
        t = peek()
        pos[0] += 1
        return t

    def expect(kind):
        t = next_()
        if t[0] != kind:
            raise PromqlError(f"expected {kind}, got {t[1]!r}")
        return t

    def parse_selector() -> Selector:
        name = expect("name")[1]
        sel = Selector(metric=name)
        if peek()[0] == "lbrace":
            next_()
            while peek()[0] != "rbrace":
                lbl = expect("name")[1]
                op = expect("op")[1]
                if op not in ("=", "!=", "=~", "!~"):
                    raise PromqlError(f"bad matcher op {op}")
                val = expect("str")[1][1:-1]
                sel.matchers.append((lbl, op, val))
                if peek()[0] == "op" and peek()[1] == ",":
                    next_()
            expect("rbrace")
        if peek()[0] == "lbrack":
            next_()
            parts = []  # "5m" lexes as num "5" + name "m": join tokens
            while peek()[0] not in ("rbrack", "eof"):
                parts.append(next_()[1])
            sel.range_s = parse_duration_s("".join(parts))
            expect("rbrack")
        return sel

    def parse_expr() -> Query:
        t = peek()
        if t[0] == "name" and t[1] in _AGGS:
            agg = next_()[1]
            by = []
            if peek()[0] == "name" and peek()[1] == "by":
                next_()
                expect("lparen")
                while peek()[0] != "rparen":
                    by.append(expect("name")[1])
                    if peek()[1] == ",":
                        next_()
                expect("rparen")
            expect("lparen")
            inner = parse_expr()
            expect("rparen")
            if peek()[0] == "name" and peek()[1] == "by":
                next_()
                expect("lparen")
                while peek()[0] != "rparen":
                    by.append(expect("name")[1])
                    if peek()[1] == ",":
                        next_()
                expect("rparen")
            inner.agg = agg
            inner.by = by
            return inner
        if t[0] == "name" and t[1] in _RATES:
            fn = next_()[1]
            expect("lparen")
            sel = parse_selector()
            expect("rparen")
            if not sel.range_s:
                raise PromqlError(f"{fn}() needs a [range]")
            return Query(selector=sel, rate_fn=fn)
        return Query(selector=parse_selector())

    q_ast = parse_expr()
    t = peek()
    if t[0] == "op" and t[1] in "*/+-":
        op = next_()[1]
        num = expect("num")[1]
        q_ast.scalar_op = op
        q_ast.scalar = float(num)
    if peek()[0] != "eof":
        raise PromqlError(f"trailing input: {peek()[1]!r}")
    return q_ast


# -- evaluation --------------------------------------------------------------

def _mangle(s: str) -> str:
    return "".join(c if c.isalnum() else "_" for c in s)


def _resolve_metric(db: Database, name: str):
    """-> (table, value_column, tag_columns, pre_filters, labels_col).

    pre_filters: [(column, code), ...] row filters identifying the metric;
    labels_col: json-encoded label column (series identity) or None.
    """
    # self-telemetry: deepflow_system_<metric>_<value> with dots mangled,
    # e.g. deepflow_system_agent_sender_sent_frames
    if name.startswith("deepflow_system_"):
        suffix = name[len("deepflow_system_"):]
        table = db.table("deepflow_system.deepflow_system")
        mdict, vdict = table.dicts["metric_name"], table.dicts["value_name"]
        # longest metric-name match first: mangling can make one name a
        # prefix of another, and first-match would be ingest-order dependent
        candidates = sorted(enumerate(mdict.snapshot()),
                            key=lambda kv: -len(kv[1]))
        for mi, mn in candidates:
            if not mn or not suffix.startswith(_mangle(mn) + "_"):
                continue
            rest = suffix[len(_mangle(mn)) + 1:]
            for vi, vn in enumerate(vdict.snapshot()):
                if vn and _mangle(vn) == rest:
                    # host/agent_id stay real columns: series split per
                    # agent and matchable alongside the json tags
                    return (table, "value", ["tag_json", "host", "agent_id"],
                            [("metric_name", mi), ("value_name", vi)],
                            "tag_json")
        # fall through: a remote-write metric may share the prefix
    for prefix, (tname, tags) in _FAMILIES.items():
        if name.startswith(prefix):
            col = name[len(prefix):]
            table = db.table(tname)
            if col in table.columns:
                return table, col, tags, None, None
            break  # fall through: maybe a remote-write metric with a
            # name that happens to share the family prefix
    # remote-write samples: any metric name, labels in labels_json
    table = db.table("prometheus.samples")
    code = table.dicts["metric_name"].lookup(name)
    if code is None:
        raise PromqlError(f"unknown metric {name!r}")
    return (table, "value", ["labels_json"], [("metric_name", code)],
            "labels_json")


def _compile(pattern: str):
    try:
        return re.compile(pattern)  # PromQL regexes are anchored (fullmatch)
    except re.error as e:
        raise PromqlError(f"bad regex {pattern!r}: {e}") from None


def _compile_matchers(table, sel, labels_col):
    """Precompute chunk-independent matcher state -> per-chunk appliers.
    Dictionary scans and regex compilation happen ONCE, not per chunk."""
    appliers = []
    for lbl, op, val in sel.matchers:
        negate = op in ("!=", "!~")
        # json-labeled metrics: remote-write user labels ALWAYS match via
        # the json column (they'd be shadowed by same-named universal tag
        # columns); self-telemetry prefers real columns (host/agent_id) and
        # falls back to the json tags
        if labels_col is not None and (
                labels_col == "labels_json" or lbl not in table.columns):
            ids = _labels_json_ids(table, lbl, op, val, labels_col)
            appliers.append(("isin", labels_col, ids, negate))
            continue
        if lbl not in table.columns:
            raise PromqlError(f"unknown label {lbl!r}")
        spec = table.columns[lbl]
        if spec.kind == "str":
            if op in ("=", "!="):
                code = table.dicts[lbl].lookup(val)
                appliers.append((
                    "eq", lbl,
                    code if code is not None else 0xFFFFFFFF, negate))
            else:
                rx = _compile(val)
                ids = table.dicts[lbl].match_ids(
                    lambda s: rx.fullmatch(s) is not None)
                appliers.append(("isin", lbl, ids, negate))
        elif spec.kind == "enum":
            if op in ("=~", "!~"):
                rx = _compile(val)
                ids = np.asarray(
                    [i for i, s in enumerate(spec.enum_values)
                     if rx.fullmatch(s)], dtype=np.uint16)
                appliers.append(("isin", lbl, ids, negate))
            else:
                try:
                    idx = spec.enum_values.index(val)
                except ValueError:
                    idx = 0xFFFF
                appliers.append(("eq", lbl, idx, negate))
        else:
            code = int(val) if val.isdigit() else None
            appliers.append(("eq", lbl, code, negate))
    return appliers


def _apply_matchers(appliers, ch) -> np.ndarray | None:
    mask = None
    for kind, lbl, data, negate in appliers:
        arr = ch[lbl]
        if kind == "eq":
            m = (np.zeros(len(arr), bool) if data is None
                 else arr == arr.dtype.type(data))
        else:
            m = np.isin(arr, data)
        if negate:
            m = ~m
        mask = m if mask is None else (mask & m)
    return mask


def evaluate(db: Database, query: str | Query, start_s: int, end_s: int,
             step_s: int = 15) -> list[dict]:
    """Range evaluation -> prometheus matrix result."""
    if isinstance(query, str):
        query = parse(query)
    sel = query.selector
    table, col, tags, pre_filters, labels_col = _resolve_metric(
        db, sel.metric)

    appliers = _compile_matchers(table, sel, labels_col)
    # remote-write clients send CUMULATIVE counters (standard Prometheus),
    # and dfstats self-telemetry snapshots cumulative process counters;
    # internal flow_metrics tables hold per-interval DELTA samples.
    # rate()/irate()/increase() must switch semantics accordingly.
    counter_mode = table.name in ("prometheus.samples",
                                  "deepflow_system.deepflow_system")
    chunks = table.snapshot()
    times, values, tag_arrays = [], [], {t: [] for t in tags}
    # prefetch must cover the instant-vector 300s staleness lookback too
    window = max(sel.range_s or 0, 300)
    for ch in chunks:
        if not ch or not len(ch["time"]):
            continue
        t = ch["time"].astype(np.int64)
        # schema convention (same as engine._col_val): u64 time columns are
        # nanoseconds, u32 are epoch seconds
        if table.columns["time"].kind == "u64":
            t = t // 1_000_000_000
        mask = (t >= start_s - window) & (t <= end_s)
        for pf_col, pf_code in (pre_filters or []):
            mask &= ch[pf_col] == pf_code
        m = _apply_matchers(appliers, ch)
        if m is not None:
            mask &= m
        idx = np.flatnonzero(mask)
        if not len(idx):
            continue
        times.append(t[idx])
        values.append(ch[col][idx].astype(np.float64))
        for lbl in tags:
            tag_arrays[lbl].append(ch[lbl][idx])
    if not times:
        return []
    t_all = np.concatenate(times)
    v_all = np.concatenate(values)
    tag_all = {lbl: np.concatenate(tag_arrays[lbl]) for lbl in tags}

    # series identity is ALWAYS the full tag set: aggregation happens across
    # evaluated series in _aggregate_series (grouped by the `by` labels), never
    # by pre-merging raw samples — pre-merging makes every aggregate except
    # sum(rate(...)) wrong (e.g. instant sum() would return one sample, count()
    # would return 1).
    group_labels = [g for g in tags if g in tag_all]
    key = np.zeros(len(t_all), dtype=np.int64)
    for lbl in group_labels:
        _, inv = np.unique(tag_all[lbl], return_inverse=True)
        # re-densify after every fold: key stays < n_rows, so the product
        # is bounded by n_rows^2 and can't overflow int64 even with many
        # high-cardinality labels
        _, key = np.unique(key * (int(inv.max(initial=0)) + 1) + inv,
                           return_inverse=True)

    out = []
    steps = np.arange(start_s, end_s + 1, step_s)
    for gk in np.unique(key):
        gmask = key == gk
        gt, gv = t_all[gmask], v_all[gmask]
        order = np.argsort(gt, kind="stable")
        gt, gv = gt[order], gv[order]
        labels = {"__name__": sel.metric}
        gi = np.flatnonzero(gmask)[0]
        for lbl in group_labels:
            spec = table.columns[lbl]
            raw = tag_all[lbl][gi]
            if lbl == labels_col and spec.kind == "str":
                import json as _json
                try:
                    labels.update(_json.loads(
                        table.dicts[lbl].decode(int(raw)) or "{}"))
                except ValueError:
                    pass
            elif spec.kind == "str":
                labels[lbl] = table.dicts[lbl].decode(int(raw))
            elif spec.kind == "enum":
                labels[lbl] = spec.enum_values[int(raw)]
            else:
                labels[lbl] = str(int(raw))
        samples = []
        # gt is sorted: each step's window is a searchsorted slice, O(log n)
        # per step instead of an O(n) mask (matters now that aggregates
        # evaluate every series)
        for ts in steps:
            if query.rate_fn:
                lo = ts - sel.range_s
                i0 = int(np.searchsorted(gt, lo, side="right"))
                i1 = int(np.searchsorted(gt, ts, side="right"))
                if i1 <= i0:
                    continue
                if counter_mode:
                    v = _counter_rate(gt[i0:i1], gv[i0:i1], query.rate_fn,
                                      sel.range_s, float(lo), float(ts))
                    if v is not None:
                        samples.append((int(ts), v))
                    continue
                if query.rate_fn == "irate":
                    # instantaneous: the last two DISTINCT timestamps in
                    # the window, with co-timestamped rows summed (a series
                    # can hold several rows per second)
                    wt, wv = gt[i0:i1], gv[i0:i1]
                    uts, inv = np.unique(wt, return_inverse=True)
                    if len(uts) < 2:
                        continue
                    sums = np.bincount(inv, weights=wv)
                    dt = float(uts[-1] - uts[-2])
                    samples.append((int(ts), float(sums[-1]) / dt))
                    continue
                total = float(gv[i0:i1].sum())
                if query.rate_fn == "rate":
                    total /= max(sel.range_s, 1e-9)
                samples.append((int(ts), total))
            else:
                i1 = int(np.searchsorted(gt, ts, side="right"))
                if i1 == 0:
                    continue
                # instant: most recent sample within 5m lookback
                if ts - gt[i1 - 1] > 300:
                    continue
                samples.append((int(ts), float(gv[i1 - 1])))
        if samples:
            out.append({"metric": labels, "values": samples})

    if query.agg:
        out = _aggregate_series(out, query.agg, query.by)
    if query.scalar_op:
        for series in out:
            series["values"] = [
                (t, _scalar(v, query.scalar_op, query.scalar))
                for t, v in series["values"]]
    return out


def _labels_json_ids(table, lbl: str, op: str, val: str,
                     labels_col: str = "labels_json") -> np.ndarray:
    """Matching dictionary ids for a matcher over a json label set.
    (Negation is applied by the caller.)"""
    import json as _json

    def get(s: str) -> str:
        try:
            return str(_json.loads(s or "{}").get(lbl, ""))
        except ValueError:
            return ""

    if op in ("=", "!="):
        pred = lambda s: get(s) == val  # noqa: E731
    else:
        rx = _compile(val)
        pred = lambda s: rx.fullmatch(get(s)) is not None  # noqa: E731
    return table.dicts[labels_col].match_ids(pred)


def _counter_rate(wt: np.ndarray, wv: np.ndarray, fn: str, range_s: float,
                  range_lo: float, range_hi: float) -> float | None:
    """Prometheus counter semantics over one series window: monotonic
    cumulative values with reset detection (a drop means the counter
    restarted at ~0, so the post-reset value IS the increase), and the
    upstream extrapolatedRate window-boundary extrapolation."""
    if len(wt) < 2:
        return None
    if fn == "irate":
        # dedup to distinct timestamps (remote-write retries re-send batches;
        # last value wins for a cumulative counter), then take the last pair
        uts = np.unique(wt)
        if len(uts) < 2:
            return None
        # last row at each of the two last distinct timestamps
        i_last = int(np.searchsorted(wt, uts[-1], side="right")) - 1
        i_prev = int(np.searchsorted(wt, uts[-2], side="right")) - 1
        dv = float(wv[i_last] - wv[i_prev])
        if dv < 0:  # reset between the two points
            dv = float(wv[i_last])
        dt = float(uts[-1] - uts[-2])
        return dv / dt
    diffs = np.diff(wv)
    # increase = sum of positive deltas; at a reset the post-reset value is
    # the delta (counter restarted from ~0)
    increase = float(np.where(diffs >= 0, diffs, wv[1:]).sum())
    # extrapolate to the window bounds (promql/functions.go extrapolatedRate):
    # extend by up to half the average sample spacing, or to the boundary if
    # it's closer than 1.1x spacing; never extrapolate past the counter's
    # implied zero crossing
    sampled = float(wt[-1] - wt[0])
    if sampled <= 0:
        return None
    avg_spacing = sampled / (len(wt) - 1)
    threshold = avg_spacing * 1.1
    to_start = float(wt[0]) - range_lo
    to_end = range_hi - float(wt[-1])
    if to_start >= threshold:
        to_start = avg_spacing / 2
    if increase > 0 and wv[0] >= 0:
        to_zero = sampled * (float(wv[0]) / increase)
        to_start = min(to_start, to_zero)
    if to_end >= threshold:
        to_end = avg_spacing / 2
    increase *= (sampled + to_start + to_end) / sampled
    if fn == "increase":
        return increase
    return increase / max(range_s, 1e-9)


def _scalar(v: float, op: str, s: float) -> float:
    if op == "*":
        return v * s
    if op == "/":
        return v / s if s else 0.0
    if op == "+":
        return v + s
    return v - s


def _aggregate_series(series: list[dict], agg: str,
                      by: list[str]) -> list[dict]:
    groups: dict[tuple, list] = {}
    for s in series:
        key = tuple((lbl, s["metric"].get(lbl, "")) for lbl in by)
        groups.setdefault(key, []).append(s)
    out = []
    for key, members in groups.items():
        merged: dict[int, list[float]] = {}
        for s in members:
            for t, v in s["values"]:
                merged.setdefault(t, []).append(v)
        labels = dict(key)
        vals = []
        for t in sorted(merged):
            vs = merged[t]
            if agg == "sum":
                vals.append((t, float(sum(vs))))
            elif agg == "avg":
                vals.append((t, float(sum(vs) / len(vs))))
            elif agg == "min":
                vals.append((t, float(min(vs))))
            elif agg == "max":
                vals.append((t, float(max(vs))))
            else:  # count
                vals.append((t, float(len(vs))))
        out.append({"metric": labels, "values": vals})
    return out
