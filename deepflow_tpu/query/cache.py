"""Querier-level result + partial-aggregate cache with exact invalidation.

Two layers, both keyed by (table name, whitespace-normalized SQL) and
validated by monotonic change tokens — never by TTL:

- whole-result cache: validated against ``table.sync_state()``
  ([write watermark, [[dict, gen, len], ...]]). Any append, trim, load or
  dictionary rebuild changes the token, so a hit is always exact.
- per-time-bucket partial cache: aggregate queries are sliced into the
  table's 60s bucket grid; each bucket's ENCODED partial
  (engine.execute_partial(encoded=True)) is cached against that bucket's
  write mark + the dictionary gens. An append invalidates only the
  buckets it touched — warm repeats recompute nothing and re-scan only
  stale buckets, then engine.combine_partials folds the slices back into
  one exact partial.

The token is read BEFORE executing: a write racing the fill can only
make the stored token stale (harmless recompute next time), never let a
stale entry validate.

Tiered storage keeps these tokens exact through persistence events by
bumping the SAME table watermark the token reads: attaching a restored
tier, confirming a flush (RAM chunks swapped for mmap'd segments — the
result set is unchanged but the backing store is not), and evicting
segments (note_tier_evict, which also marks the evicted time span so
bucket partials over it invalidate) all advance it. Rollup appends are
ordinary writes to the rollup TABLE, and datasource selection swaps the
table object before the cache lookup — raw and rollup entries key
separately, so a coarser answer can never serve a raw-table hit.

Admission goes through the learned cost hook (query/costmodel.py —
"A Learned Performance Model for TPUs" motivates modeled rather than
hand-tuned plan choices): queries whose observed cold cost stays under
DF_QUERY_CACHE_MIN_NS are not worth an entry. DF_QUERY_CACHE=0 bypasses
entirely.

Self-telemetry: one ``query.cache`` hop ledger (PR 2 conventions) —
emitted=lookups, delivered=hits, dropped{miss|stale|bypass}; evictions
are a separate counter surfaced in /v1/health.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict

from deepflow_tpu.query import engine
from deepflow_tpu.query import pool as qpool
from deepflow_tpu.query import qtrace
from deepflow_tpu.query import sql as S
from deepflow_tpu.query.costmodel import KernelCostModel


def normalize_sql(sql: str) -> str:
    return " ".join(sql.split())


def change_token(table) -> list:
    """Result-validity token: [watermark, [[dict, gen], ...]].

    Deliberately NOT the full sync_state(): dictionary LENGTH is
    excluded because dictionaries can grow without any row write — the
    federation coordinator encodes remote shard strings into its local
    dictionaries while remapping (cluster/dictsync.py). Growth is
    append-only within a gen, and rows only ever reference ids minted by
    writes (which bump the watermark), so extra entries cannot change
    any query answer: decode of existing ids, collation order, LIKE and
    equality pushdown all come out identical. Gen flips (compaction,
    reload) rebind ids and DO invalidate."""
    wm, dicts = table.sync_state()
    return [wm, [[n, g] for n, g, _l in dicts]]


class QueryCache:
    def __init__(self, max_entries: int = 128, max_buckets: int = 512,
                 telemetry=None) -> None:
        self.max_entries = max_entries
        self.max_buckets = max_buckets
        self._lock = threading.Lock()
        # (table, sql) -> (token, QueryResult)
        self._results: OrderedDict[tuple, tuple] = OrderedDict()
        # (table, sql) -> OrderedDict{bucket: (mark, gens, partial)}
        self._buckets: OrderedDict[tuple, OrderedDict] = OrderedDict()
        self.counters = {"hits": 0, "misses": 0, "stale": 0, "bypass": 0,
                         "evictions": 0, "bucket_hits": 0,
                         "bucket_misses": 0, "bucket_pruned": 0,
                         "dist_hits": 0}
        self._hop = telemetry.hop("query.cache") if telemetry else None
        # distributed partial-cache hook (cluster/partialcache.py):
        # dist(table, key, [bucket, ...], gens) -> {bucket: partial} of
        # slices a warm peer already computed, remapped into LOCAL
        # dictionary ids — they slot into the bucket store exactly like
        # a local scan's output. None = single-node, zero overhead.
        self.dist = None
        # learned cold-cost per cached query shape (admission hook)
        self.cost = KernelCostModel(kernels=("cold", "warm"))

    # -- helpers -------------------------------------------------------------

    _OUTCOME_KEY = {"hit": "hits", "miss": "misses", "stale": "stale",
                    "bypass": "bypass"}

    def _account(self, outcome: str) -> None:
        with self._lock:
            self.counters[self._OUTCOME_KEY[outcome]] += 1
        if self._hop is not None:
            if outcome == "hit":
                self._hop.account(emitted=1, delivered=1)
            else:
                self._hop.account(emitted=1, dropped=1, reason=outcome)

    def _enabled(self) -> bool:
        return os.environ.get("DF_QUERY_CACHE", "1") != "0"

    def _min_ns(self) -> float:
        try:
            return float(os.environ.get("DF_QUERY_CACHE_MIN_NS", "0"))
        except ValueError:
            return 0.0

    @staticmethod
    def _copy_result(res: engine.QueryResult) -> engine.QueryResult:
        return engine.QueryResult(columns=list(res.columns),
                                  values=[list(r) for r in res.values])

    # -- whole-result layer --------------------------------------------------

    def execute(self, table, sql: str, *, select=None,
                extra_key=None) -> engine.QueryResult:
        """engine.execute() through the cache. `select` is an optional
        pre-parsed (possibly rewritten — org scoping) AST to run instead
        of parsing `sql`; any rewrite not visible in the SQL text must be
        reflected in `extra_key` or rewritten variants would collide."""
        if not self._enabled():
            self._account("bypass")
            qtrace.span("cache.lookup", layer="result",
                        outcome="bypass").finish()
            return engine.execute(table, select if select is not None
                                  else sql)
        key = (table.name, normalize_sql(sql), extra_key)
        token = change_token(table)  # BEFORE executing: stale-safe
        with self._lock:
            ent = self._results.get(key)
            if ent is not None:
                self._results.move_to_end(key)
        if ent is not None and ent[0] == token:
            self._account("hit")
            self.cost.observe("warm", 1, 1.0)
            qtrace.span("cache.lookup", layer="result",
                        outcome="hit").finish()
            return self._copy_result(ent[1])
        outcome = "stale" if ent is not None else "miss"
        self._account(outcome)
        qtrace.span("cache.lookup", layer="result",
                    outcome=outcome).finish()
        t0 = time.perf_counter_ns()
        res = self._execute_cold(table, sql, key, select)
        cold_ns = time.perf_counter_ns() - t0
        self.cost.observe("cold", 1, cold_ns)
        if cold_ns >= self._min_ns():
            with self._lock:
                self._results[key] = (token, self._copy_result(res))
                self._results.move_to_end(key)
                while len(self._results) > self.max_entries:
                    self._results.popitem(last=False)
                    self.counters["evictions"] += 1
        return res

    def _execute_cold(self, table, sql: str, key: tuple, select=None):
        """Cold fill: bucketed partial plan when eligible, plain scan
        otherwise. Cold AND warm both go through the bucket partials for
        a bucketable query, so repeats are self-consistent."""
        try:
            query = select if select is not None else S.parse(sql)
            parts = self._bucket_partials(table, query, key)
            if parts is not None:
                combined = engine.combine_partials(table, query, parts)
                return engine.merge_partials(table, query, [combined])
        except engine._FastUnsupported:
            self._drop_buckets(key)
        except engine.QueryError:
            raise
        except Exception:
            self._drop_buckets(key)
        return engine.execute(table, select if select is not None else sql)

    # -- bucketed partial layer ----------------------------------------------

    def _bucketable(self, table, query: S.Select) -> bool:
        if os.environ.get("DF_QUERY_ENCODED", "1") == "0":
            return False
        norm = engine._normalize(table, query)
        if not engine._is_agg_query(norm):
            return False
        # PERCENTILE: the local scan uses exact np.percentile while the
        # partial form is a sketch — caching would change answers. LAST:
        # cross-bucket timestamp ties could resolve differently.
        if any(s.name in ("PERCENTILE", "LAST")
               for s in engine._agg_sites(norm)):
            return False
        return True

    def _bucket_partials(self, table, query: S.Select, key: tuple,
                         bucket_range: tuple[int, int] | None = None,
                         stats: dict | None = None):
        """Per-bucket encoded partials for an eligible aggregate query,
        reusing every bucket whose (write mark, dict gens) is unchanged.
        None when the query/table isn't bucketable. bucket_range=(lo, hi)
        folds only buckets with lo <= b < hi (the standing-query window
        slice); stats, when given, is filled with bucket reuse counts."""
        if not self._bucketable(table, query):
            return None
        wm, marks, wide, div = table.bucket_marks()
        tc = getattr(table, "_time_col", None)
        if div <= 0 or tc is None or wide:
            return None
        sub = marks
        if bucket_range is not None:
            lo_b, hi_b = bucket_range
            sub = {b: m for b, m in marks.items() if lo_b <= b < hi_b}
        if len(sub) > self.max_buckets:
            return None
        gens = tuple((n, g) for n, g, _l in table.sync_state()[1])
        with self._lock:
            store = self._buckets.get(key)
            if store is None:
                store = self._buckets[key] = OrderedDict()
                self._buckets.move_to_end(key)
                while len(self._buckets) > self.max_entries:
                    self._buckets.popitem(last=False)
                    self.counters["evictions"] += 1
            # buckets trimmed off the grid can never validate again —
            # pruned against the FULL mark grid, so a windowed fold never
            # evicts slices another caller of the same key still wants
            for b in [b for b in store if b not in marks]:
                del store[b]
                self.counters["bucket_pruned"] += 1
        ordered = sorted(sub.items())
        slot: dict[int, dict] = {}
        stale: list[tuple[int, int]] = []
        for b, mark in ordered:
            with self._lock:
                ent = store.get(b)
            if ent is not None and ent[0] == mark and ent[1] == gens:
                with self._lock:
                    self.counters["bucket_hits"] += 1
                slot[b] = ent[2]
            else:
                stale.append((b, mark))
        qtrace.annotate(buckets=len(ordered), bucket_stale=len(stale))
        if stats is not None:
            stats["buckets"] = len(ordered)
            stats["bucket_hits"] = len(ordered) - len(stale)
        if stale and self.dist is not None:
            # ask a warm peer before scanning: each (mark, gens) was
            # captured BEFORE the fetch, so a write racing the network
            # round-trip can only make the stored entry stale (same
            # safety argument as the local fill path)
            try:
                got = self.dist(table, key, [b for b, _m in stale], gens)
            except Exception:
                got = {}
            if got:
                still = []
                for b, mark in stale:
                    part = got.get(b)
                    if part is not None and part.get("kind") == "agg":
                        with self._lock:
                            self.counters["dist_hits"] += 1
                            store[b] = (mark, gens, part)
                        slot[b] = part
                    else:
                        still.append((b, mark))
                if stats is not None:
                    stats["dist_hits"] = len(stale) - len(still)
                stale = still
        if stats is not None:
            stats["scanned"] = len(stale)
        if stale:
            def _scan(bm):
                b, _mark = bm
                bq = self._bucket_query(query, tc, b * div, (b + 1) * div)
                return engine.execute_partial(table, bq, encoded=True)
            # stale buckets recompute on the shared scan pool (each
            # bucket's execute_partial runs serially inside its worker —
            # the in_worker guard stops nested fan-out)
            p = qpool.get_pool()
            if p is not None and len(stale) > 1:
                outs = p.map(_scan, stale)
            else:
                outs = [_scan(bm) for bm in stale]
            for (b, mark), part in zip(stale, outs):
                if part.get("kind") != "agg":
                    return None
                with self._lock:
                    self.counters["bucket_misses"] += 1
                    store[b] = (mark, gens, part)
                slot[b] = part
        return [slot[b] for b, _m in ordered]

    @staticmethod
    def _bucket_query(query: S.Select, tc: str, lo: int,
                      hi: int) -> S.Select:
        rng = S.BinOp("AND",
                      S.BinOp(">=", S.Col(tc), S.Lit(int(lo))),
                      S.BinOp("<", S.Col(tc), S.Lit(int(hi))))
        where = rng if query.where is None else \
            S.BinOp("AND", query.where, rng)
        # ORDER BY/LIMIT apply at the merge, not per slice; HAVING rides
        # along so its aggregate sites ship in the partial (it is only
        # APPLIED at the merge)
        return S.Select(items=query.items, table=query.table, where=where,
                        group_by=query.group_by, having=query.having,
                        order_by=[], limit=None)

    def partial(self, table, sql: str, *, select=None,
                extra_key=None) -> dict:
        """engine.execute_partial(encoded=True) through the bucket cache:
        a warm shard answers a scatter by folding cached bucket slices
        instead of rescanning (shard-side half of federated caching)."""
        query = select if select is not None else sql
        if not self._enabled():
            return engine.execute_partial(table, query, encoded=True)
        key = (table.name, normalize_sql(sql), extra_key)
        try:
            if isinstance(query, str):
                query = S.parse(query)
            parts = self._bucket_partials(table, query, key)
            if parts is not None:
                return engine.combine_partials(table, query, parts)
        except engine._FastUnsupported:
            self._drop_buckets(key)
        except engine.QueryError:
            raise
        except Exception:
            self._drop_buckets(key)
        return engine.execute_partial(table, query, encoded=True)

    def standing_fold(self, table, sql: str, *, select=None,
                      extra_key=None,
                      bucket_range: tuple[int, int] | None = None
                      ) -> tuple:
        """Windowed incremental fold for standing queries
        (query/standing.py): fold ONLY the 60s buckets inside
        ``bucket_range``, reusing every cached slice (and the
        distributed partial cache via the dist hook). Keys on the SAME
        (table, sql, extra_key) as execute()/partial(), so standing and
        ad-hoc evaluations of one query share warm buckets. Returns
        (QueryResult | None, stats): None when the query isn't
        bucketable or the window holds no marked buckets — the caller
        falls back to a from-scratch execute."""
        stats = {"buckets": 0, "bucket_hits": 0, "dist_hits": 0,
                 "scanned": 0}
        if not self._enabled():
            return None, stats
        key = (table.name, normalize_sql(sql), extra_key)
        try:
            query = select if select is not None else S.parse(sql)
            parts = self._bucket_partials(table, query, key,
                                          bucket_range=bucket_range,
                                          stats=stats)
            if not parts:
                return None, stats
            combined = engine.combine_partials(table, query, parts)
            return engine.merge_partials(table, query, [combined]), stats
        except engine._FastUnsupported:
            self._drop_buckets(key)
        except engine.QueryError:
            raise
        except Exception:
            self._drop_buckets(key)
        return None, stats

    def _drop_buckets(self, key: tuple) -> None:
        with self._lock:
            self._buckets.pop(key, None)

    # -- distributed partial-cache surface ------------------------------------

    def warm_keys(self) -> list[tuple]:
        """Bucket-store keys holding at least one slice — the advert
        source for the cluster-wide partial cache (membership gossips
        digests of the shareable ones)."""
        with self._lock:
            return [k for k, v in self._buckets.items() if v]

    def peek_buckets(self, table, sql: str, extra_keys: list,
                     wanted: list) -> dict:
        """CURRENTLY-valid cached slices for the wanted buckets, under
        any of the candidate cache-key variants (the org-equivalent
        extra_key forms). Validation is against this node's own marks
        and dictionary gens — the caller (cluster/partialcache.py)
        established content equivalence with the requester separately,
        via the read-tier publish token."""
        wm, marks, wide, div = table.bucket_marks()
        if div <= 0 or wide:
            return {}
        gens = tuple((n, g) for n, g, _l in table.sync_state()[1])
        norm = normalize_sql(sql)
        out: dict[int, dict] = {}
        with self._lock:
            for ek in extra_keys:
                store = self._buckets.get((table.name, norm, ek))
                if not store:
                    continue
                for b in wanted:
                    if b in out or b not in marks:
                        continue
                    ent = store.get(b)
                    if ent is not None and ent[0] == marks[b] \
                            and ent[1] == gens:
                        out[b] = ent[2]
        return out

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {"entries": len(self._results),
                    "bucket_queries": len(self._buckets),
                    "bucket_slices": sum(len(v)
                                         for v in self._buckets.values()),
                    **self.counters,
                    "cost": self.cost.snapshot()}
