"""Transparent rollup datasource selection.

Reference analog: the querier's datasource auto-selection over the
ingester's 1m/1h/1d rollup tables (server/querier picks the coarsest
datasource whose interval divides the query's grouping). A query over a
raw `flow_metrics.*.1s` table is answered from a rollup tier instead —
byte-identically — when four things hold:

  1. every aggregate call site is the SAME decomposable aggregator the
     rollup applied to that column (Sum/Max/Min partials re-aggregate
     to the raw answer; Count/Last/Percentile do not decompose),
  2. every non-aggregate column reference is a rollup group-by tag (or
     `time` inside an aligned time() bucket),
  3. the GROUP BY is tags plus time() buckets that are multiples of
     the tier's bucket, and
  4. the WHERE is a conjunction of tag-only filters and tier-aligned
     time bounds whose upper bound closes under the rollup job's
     completeness horizon (late rows past the horizon would otherwise
     be missing from the rollup answer).

The rollup tables share the raw tables' column names, so selection is
a pure TABLE SWAP: the SQL text runs unchanged, and the query cache
keys on the table object — raw and rollup answers never collide.

Avg() and Count() reject for the same reason: rolling collapses rows,
so their denominators change — Avg over 1m rows divides by minutes,
not raw rows. The DeepFlow-style recipe (Sum(rrt_sum)/Sum(rrt_count)
over pre-summed meter pairs) stays selectable because both sides are
Sums.

PERCENTILE() takes a separate path (`sketch_percentile`): rollup tiers
carry a mergeable DDSketch state column, and a percentile over a
covered range is answered by merging those states per group — the one
documented-approximate rollup (relative error bounded by the sketch
gamma, exactly like federated percentile merges).
"""

from __future__ import annotations

import json
import logging

from deepflow_tpu.query import engine as qengine
from deepflow_tpu.query import sql as S

log = logging.getLogger("df.qdatasource")

# selectable tiers, coarsest first (a coarser answer scans fewer rows)
_TIERS = [("1d", 86400), ("1h", 3600), ("1m", 60)]

_AGG_MATCH = {"SUM": "Sum", "MAX": "Max", "MIN": "Min"}


def _family(table_name: str):
    """(family, spec) when `table_name` is a raw 1s rollup source."""
    from deepflow_tpu.server.datasource import FAMILIES
    if not table_name.endswith(".1s"):
        return None
    family = table_name[:-len(".1s")]
    spec = FAMILIES.get(family)
    return None if spec is None else (family, spec)


def _collect_nonagg_cols(e, out: set) -> None:
    """Column refs OUTSIDE aggregate call sites (agg args are validated
    against the rollup aggregators separately)."""
    if isinstance(e, S.Col):
        out.add(e.name)
    elif isinstance(e, S.Func):
        if e.name in S.AGG_FUNCS:
            return
        # an aligned time() bucket is the rollup's own grouping key, not
        # a raw-timestamp reference (_time_buckets validates its width)
        if (e.name == "TIME" and len(e.args) == 2
                and isinstance(e.args[0], S.Col)
                and e.args[0].name == "time"
                and isinstance(e.args[1], S.Lit)):
            return
        for a in e.args:
            _collect_nonagg_cols(a, out)
    elif isinstance(e, S.BinOp):
        _collect_nonagg_cols(e.left, out)
        if not isinstance(e.right, tuple):
            _collect_nonagg_cols(e.right, out)
    elif isinstance(e, S.Not):
        _collect_nonagg_cols(e.expr, out)
    elif isinstance(e, S.Case):
        for c, v in e.whens:
            _collect_nonagg_cols(c, out)
            _collect_nonagg_cols(v, out)
        if e.default is not None:
            _collect_nonagg_cols(e.default, out)


# top-level AND flattening is shared with the engine's zone-map
# constraint extraction — one definition of "conjunct" for both the
# rollup classifier and segment pruning
_conjuncts = qengine.split_conjuncts


def _time_bound(e):
    """(op, seconds) for a `time >= lo` / `time < hi` conjunct, else
    None. Only these two forms are accepted: anything else touching
    `time` disqualifies selection (mid-bucket bounds would slice rolled
    buckets that cannot be sliced)."""
    if (isinstance(e, S.BinOp) and e.op in (">=", "<")
            and isinstance(e.left, S.Col) and e.left.name == "time"
            and isinstance(e.right, S.Lit)
            and isinstance(e.right.value, int)):
        return e.op, int(e.right.value)
    return None


def _time_buckets(query: S.Select) -> list[int] | None:
    """Every time(time, N) bucket width used by the query, or None when
    some group-by entry is neither a plain column nor an aligned time
    bucket."""
    widths: list[int] = []
    for g in query.group_by:
        if isinstance(g, S.Col):
            continue
        if (isinstance(g, S.Func) and g.name == "TIME"
                and len(g.args) == 2 and isinstance(g.args[0], S.Col)
                and g.args[0].name == "time"
                and isinstance(g.args[1], S.Lit)):
            try:
                widths.append(int(g.args[1].value))
            except (TypeError, ValueError):
                return None
            continue
        return None
    # time() in SELECT items must appear in GROUP BY for an aggregate
    # query, so group_by widths are the complete set
    return widths


def _classify(table, query: S.Select, spec):
    """Shared eligibility analysis. Returns (tag_cols_ok, widths,
    lo, hi) or None when the query shape can never select a rollup:
    widths — every time() bucket used; hi — the exclusive upper time
    bound (REQUIRED: without it the window extends past any horizon)."""
    for item in query.items:
        if isinstance(item.expr, S.Star):
            return None
    if any(i.distinct for i in qengine._agg_sites(query)
           if isinstance(i, S.Func)):
        return None
    widths = _time_buckets(query)
    if widths is None:
        return None
    nonagg: set[str] = set()
    for item in query.items:
        _collect_nonagg_cols(item.expr, nonagg)
    for g in query.group_by:
        _collect_nonagg_cols(g, nonagg)
    if query.having is not None:
        _collect_nonagg_cols(query.having, nonagg)
    aliases = {i.alias for i in query.items if i.alias}
    for e, _ in query.order_by:
        if isinstance(e, S.Col) and e.name in aliases:
            continue
        if S.expr_name(e) in aliases:
            continue
        _collect_nonagg_cols(e, nonagg)
    allowed = set(spec.tags)
    # `time` outside time()/WHERE-bounds (e.g. SELECT time) would leak
    # bucket-start values where raw timestamps were asked for
    if not nonagg <= allowed:
        return None
    lo = hi = None
    if query.where is not None:
        for c in _conjuncts(query.where):
            cols: set[str] = set()
            _collect_nonagg_cols(c, cols)
            if "time" not in cols:
                if not cols <= allowed:
                    return None
                continue
            tb = _time_bound(c)
            if tb is None:
                return None
            if tb[0] == ">=":
                lo = tb[1] if lo is None else max(lo, tb[1])
            else:
                hi = tb[1] if hi is None else min(hi, tb[1])
    if hi is None:
        return None
    return widths, lo, hi


def _pick_tier(db, family: str, widths, lo, hi, horizons):
    """Coarsest tier that answers exactly, or None."""
    for sfx, bucket in _TIERS:
        if any(w % bucket for w in widths):
            continue
        if hi % bucket or (lo is not None and lo % bucket):
            continue
        if hi > horizons.get((family, sfx), 0):
            continue  # late rows past the horizon not yet rolled
        try:
            return db.table(f"{family}.{sfx}"), sfx, bucket
        except KeyError:
            continue
    return None


def select_rollup(db, table, query: S.Select, horizons):
    """(rollup_table, info) when `query` over raw `table` is answered
    byte-identically by a rollup tier; None otherwise (caller keeps the
    raw table). `horizons` is RollupJob.horizons()."""
    fam = _family(table.name)
    if fam is None:
        return None
    family, spec = fam
    try:
        query = qengine._normalize(table, query)
    except qengine.QueryError:
        return None  # let the raw path raise the real error
    sites = qengine._agg_sites(query)
    if not sites:
        return None  # row-level query: raw timestamps must survive
    for site in sites:
        fn = _AGG_MATCH.get(site.name)
        if (fn is None or site.distinct or len(site.args) != 1
                or not isinstance(site.args[0], S.Col)
                or spec.aggs.get(site.args[0].name) != fn):
            return None
    shape = _classify(table, query, spec)
    if shape is None:
        return None
    picked = _pick_tier(db, family, *shape, horizons)
    if picked is None:
        return None
    rtable, sfx, bucket = picked
    return rtable, {"datasource": rtable.name, "bucket_s": bucket,
                    "tier": sfx}


def sketch_percentile(db, table, query: S.Select, horizons):
    """(QueryResult, info) for a PERCENTILE query answered from rollup
    DDSketch state; None when the query must run raw. Approximate
    within the sketch's gamma bound — mirrors the documented federated
    percentile merge semantics."""
    from deepflow_tpu.cluster.sketch import HistogramSketch
    fam = _family(table.name)
    if fam is None or not fam[1].sketches:
        return None
    family, spec = fam
    sketch_of = {src: sc for sc, src in spec.sketches.items()}
    try:
        query = qengine._normalize(table, query)
    except qengine.QueryError:
        return None
    if query.having is not None or query.order_by or query.limit:
        return None
    # every item must be a group key or exactly PERCENTILE(<covered>, p)
    sites: list[tuple[int, str, float]] = []  # (item idx, sketch col, p)
    group_keys = list(query.group_by)
    for idx, item in enumerate(query.items):
        e = item.expr
        if (isinstance(e, S.Func) and e.name == "PERCENTILE"
                and len(e.args) == 2 and isinstance(e.args[0], S.Col)
                and e.args[0].name in sketch_of
                and isinstance(e.args[1], S.Lit)):
            sites.append((idx, sketch_of[e.args[0].name],
                          float(e.args[1].value)))
            continue
        if e in group_keys:
            continue
        return None
    if not sites:
        return None
    shape = _classify(table, query, spec)
    if shape is None:
        return None
    picked = _pick_tier(db, family, *shape, horizons)
    if picked is None:
        return None
    rtable, sfx, bucket = picked
    need_sketches = sorted({sc for _, sc, _ in sites})
    if any(sc not in rtable.columns for sc in need_sketches):
        return None
    # fetch the group keys + sketch states as plain rows, merge states
    # per group in the sketch domain, then emit in the query's layout
    fetch = S.Select(
        items=([S.SelectItem(g, f"k{j}")
                for j, g in enumerate(group_keys)]
               + [S.SelectItem(S.Col(sc), sc) for sc in need_sketches]),
        table=query.table, where=query.where)
    res = qengine.execute(rtable, fetch)
    nk = len(group_keys)
    merged: dict[tuple, dict] = {}
    for row in res.values:
        key = tuple(row[:nk])
        cur = merged.get(key)
        if cur is None:
            cur = merged[key] = {sc: HistogramSketch()
                                 for sc in need_sketches}
        for j, sc in enumerate(need_sketches):
            state = row[nk + j]
            if state:
                try:
                    cur[sc].merge(
                        HistogramSketch.from_dict(json.loads(state)))
                except (ValueError, TypeError):
                    log.warning("undecodable sketch state skipped")
    names = [i.alias or S.expr_name(i.expr) for i in query.items]
    key_idx = {repr(g): j for j, g in enumerate(group_keys)}
    site_by_item = {idx: (sc, p) for idx, sc, p in sites}
    rows = []
    for key in sorted(merged, key=repr):
        sk = merged[key]
        row = []
        for idx, item in enumerate(query.items):
            if idx in site_by_item:
                sc, p = site_by_item[idx]
                row.append(sk[sc].percentile(p))
            else:
                row.append(key[key_idx[repr(item.expr)]])
        rows.append(row)
    result = qengine.QueryResult(columns=names, values=rows)
    return result, {"datasource": rtable.name, "bucket_s": bucket,
                    "tier": sfx, "approx": "ddsketch",
                    "sites": len(sites)}
