"""Dogfooded query tracing: per-query span trees across the cluster.

Every query the querier serves (DF-SQL / PromQL / Tempo) gets a trace id
and a span tree — coordinator parse/plan, federation scatter, per-shard
``/v1/shard/exec``, zone/bloom prune decisions, morsel scans, segment
cache fetches, partial-cache dist fetches, dict-sync remaps, merge.
Spans land in the system's OWN ``deepflow_system.query_trace`` table (the
same self-monitoring channel DFSTATS uses), so the existing Tempo search
API and flame-graph assembler render the querier's internals exactly
like any instrumented workload: the observability pipeline observing
itself.

Design constraints that shaped the module:

* **One tracer per Server** (like ``Telemetry``): tests run several
  servers per process, so the only process-global state is a
  thread-local pointing at the ACTIVE trace buffer.  ``span()`` /
  ``annotate()`` / ``bump()`` read that thread-local and are no-ops
  (one dict lookup, no allocation) when no trace is active — the
  query path stays well under the 2% overhead gate when tracing is
  off or the query is sampled out.
* **Propagation is explicit**: pool workers and fan-out threads don't
  inherit thread-locals, so ``current_buf()``/``use_buf()`` let the
  scan pool and the federation scatter re-attach a worker thread to
  the submitting query's buffer.  Cross-process propagation rides the
  scatter body as a small ``qtrace`` dict (see ``ctx_for_wire``).
* **Sampling is head+tail**: deterministic head sampling on the trace
  id (coordinator and shards agree without coordination), with a tail
  upgrade that always keeps slow or errored queries.  Dropped traces
  are accounted in the ``query.trace`` hop ledger with a reason, so
  ``emitted == delivered + dropped`` holds for spans like it does for
  frames everywhere else in the pipeline.

Kill-switch: ``DF_QUERY_TRACE=0`` (read live, like ``DF_NO_SELFMON``).
Knobs: ``DF_QUERY_TRACE_SAMPLE`` (keep 1/N of bulk traces, default 8 —
bulk traces of healthy fast queries are downsampled so the span sink
stays off the query path's overhead budget; slow/errored queries are
always tail-kept regardless), ``DF_QUERY_TRACE_SLOW_MS`` (tail-keep
threshold, default 250).
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time

log = logging.getLogger("df.qtrace")

# hard cap on spans buffered per trace: a runaway instrumented loop
# degrades to a truncated trace + a counted drop, never unbounded memory
MAX_SPANS_PER_TRACE = 512

# completed traces queued on the tracer before a background flush is
# kicked; readers (flush/snapshot/pending_spans/Tempo search) drain
# inline, so this only bounds how much a write-only workload can buffer
_DRAIN_TRACES = 128

_tls = threading.local()


def _enabled() -> bool:
    return os.environ.get("DF_QUERY_TRACE", "") not in ("0", "false", "off")


def _sample_n() -> int:
    try:
        return max(1, int(os.environ.get("DF_QUERY_TRACE_SAMPLE", "8")))
    except ValueError:
        return 8


def _slow_ns() -> int:
    ms = os.environ.get("DF_QUERY_TRACE_SLOW_MS")
    if ms is None:
        return 250_000_000
    try:
        return int(float(ms) * 1e6)
    except ValueError:
        return 250_000_000


# span/trace ids: a process-unique counter seeded from os.urandom.
# uuid4 costs ~10us a call and a traced query mints ~9 ids, which alone
# blows the <2% overhead gate; next() on an itertools.count is a single
# C-level op (atomic under the GIL) and the random 64-bit start keeps
# ids from colliding across shard processes of one trace.
_ids = itertools.count(int.from_bytes(os.urandom(8), "big"))


def _new_id() -> str:
    return "%016x" % (next(_ids) & 0xFFFFFFFFFFFFFFFF)


def _head_keep(trace_id: str, n: int) -> bool:
    """Deterministic head-sampling decision: every process holding the
    same trace id reaches the same verdict without coordination."""
    if n <= 1:
        return True
    # ids are hex and the low digits carry the entropy; int() parses at
    # C speed (a python hash loop over 32 chars costs ~3us/query).  The
    # splitmix-style finalizer matters: counter-minted ids advance by a
    # near-constant stride per trace, and a bare modulo over a constant
    # stride keeps 0% or 2/n of traces instead of 1/n.
    try:
        v = int(trace_id[-16:], 16)
    except ValueError:
        v = -1
    if v >= 0:
        v ^= v >> 33
        v = (v * 0xFF51AFD7ED558CCD) & 0xFFFFFFFFFFFFFFFF
        v ^= v >> 33
        return v % n == 0
    # stable across processes (unlike hash()) for non-hex foreign ids
    h = 0
    for ch in trace_id:
        h = (h * 131 + ord(ch)) & 0xFFFFFFFF
    return h % n == 0


class Span:
    """One timed operation.  Mutable while open; ``to_dict()`` after
    close yields the wire/table shape shared with query/tracing.py."""

    __slots__ = ("span_id", "parent_span_id", "name", "start_ns", "end_ns",
                 "cpu_start_ns", "cpu_ns", "status", "attrs", "_buf",
                 "_prev")

    def __init__(self, buf: "_TraceBuf", name: str,
                 parent_span_id: str, attrs: dict | None) -> None:
        self.span_id = _new_id()
        self.parent_span_id = parent_span_id
        self.name = name
        self.start_ns = time.time_ns()
        # thread CPU time is a real syscall (no vDSO) and only EXPLAIN
        # ANALYZE's stage table reads cpu_ns, so bulk traces skip both
        # clock reads — two syscalls x ~9 spans/query adds up against
        # the overhead gate
        self.cpu_start_ns = time.thread_time_ns() if buf.capture else 0
        self.end_ns = 0
        self.cpu_ns = 0
        self.status = "ok"
        # callers build attrs fresh from **kwargs; take ownership
        self.attrs = attrs if attrs is not None else {}
        self._buf = buf

    # -- context manager ----------------------------------------------------

    def __enter__(self) -> "Span":
        self._prev = getattr(_tls, "span", None)
        _tls.span = self
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.status = "error"
            self.attrs.setdefault("error", f"{exc_type.__name__}: {exc}")
        self.finish()
        _tls.span = self._prev

    def finish(self) -> None:
        if self.end_ns:
            return
        self.end_ns = time.time_ns()
        if self.cpu_start_ns:
            self.cpu_ns = time.thread_time_ns() - self.cpu_start_ns
        self._buf.add(self)

    def annotate(self, **attrs) -> None:
        self.attrs.update(attrs)

    def bump(self, key: str, n: int = 1) -> None:
        self.attrs[key] = self.attrs.get(key, 0) + n

    @property
    def duration_ns(self) -> int:
        return max(0, self.end_ns - self.start_ns)

    @property
    def trace_id(self) -> str:
        return self._buf.trace_id

    def trace_spans(self) -> list[dict]:
        """Finished span dicts of this span's trace so far — the
        capture=True hand-back used by EXPLAIN ANALYZE."""
        buf = self._buf
        finished = list(buf.spans)  # snapshot; append-only under GIL
        return [s.to_dict(buf) for s in finished]

    def to_dict(self, buf: "_TraceBuf") -> dict:
        return {
            "trace_id": buf.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "name": self.name,
            "service": buf.service,
            "start_ns": self.start_ns,
            "end_ns": self.end_ns,
            "duration_ns": self.duration_ns,
            "cpu_ns": max(0, self.cpu_ns),
            "status": self.status,
            "kind": "query",
            "attrs": self.attrs,
        }


class _RootSpan(Span):
    """Root of a trace on this process: entering installs the trace
    buffer on the thread-local; exiting restores the previous buffer
    and hands the finished trace to the tracer for sampling verdict,
    ledger accounting, and sink flush.  enter/exit are flattened (no
    super() chain through Span.__exit__/finish/add): the root runs once
    per query and each interpreter frame on this path is billed against
    the <2% overhead gate."""

    __slots__ = ("_prev_buf",)

    def __enter__(self) -> "_RootSpan":
        self._prev_buf = getattr(_tls, "buf", None)
        self._prev = getattr(_tls, "span", None)
        _tls.buf = self._buf
        _tls.span = self
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.status = "error"
            self.attrs.setdefault("error", f"{exc_type.__name__}: {exc}")
        if not self.end_ns:
            self.end_ns = time.time_ns()
            if self.cpu_start_ns:
                self.cpu_ns = time.thread_time_ns() - self.cpu_start_ns
            self._buf.spans.append(self)
        _tls.span = self._prev
        _tls.buf = self._prev_buf
        self._buf.tracer._complete(self._buf)


class _NullSpan:
    """Returned when no trace is active: all methods are no-ops and the
    singleton is reused, so disabled-path cost is one attr lookup."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass

    def finish(self) -> None:
        pass

    def annotate(self, **attrs) -> None:
        pass

    def bump(self, key: str, n: int = 1) -> None:
        pass

    @property
    def trace_id(self) -> str:
        return ""

    @property
    def duration_ns(self) -> int:
        return 0

    def trace_spans(self) -> list:
        return []


_NULL_SPAN = _NullSpan()


class _TraceBuf:
    """All spans of one query on one process.  ``add`` is safe from
    morsel-scan worker threads without a lock: list.append is atomic
    under the GIL and the bookkeeping races are benign."""

    __slots__ = ("tracer", "trace_id", "root", "sampled", "capture",
                 "spans", "overflow", "_done")

    def __init__(self, tracer: "QueryTracer", trace_id: str,
                 sampled: bool | None, capture: bool) -> None:
        self.tracer = tracer
        self.trace_id = trace_id
        self.root: Span | None = None
        self.sampled = sampled      # head verdict; None = not yet decided
        self.capture = capture      # EXPLAIN ANALYZE: hand spans back
        self.spans: list[Span] = []
        self.overflow = 0
        self._done = False

    @property
    def service(self) -> str:
        return self.tracer.service

    def add(self, span: Span) -> None:
        # the Span OBJECT is buffered; the dict conversion runs at flush
        # (off the query thread for periodic flushes) or on read.
        # Lock-free: list.append is atomic under the GIL, and the
        # _done/overflow checks race benignly (a straggler span landing
        # during root exit misses the completion snapshot, it never
        # corrupts it) — the lock acquisition per span was a measurable
        # slice of the query-path overhead budget
        if self._done:
            return
        spans = self.spans
        if len(spans) >= MAX_SPANS_PER_TRACE:
            self.overflow += 1
            return
        spans.append(span)


class QueryTracer:
    """Per-server query tracer: root-span lifecycle, sampling verdicts,
    ledger accounting, buffered sink into deepflow_system.query_trace.

    ``sink`` is ``Callable[[list[dict]], None]`` taking finished span
    dicts (usually ``Server`` appending rows to the system table); when
    None, spans are only visible through ``pending_spans`` until a sink
    is attached (tests, or early startup)."""

    def __init__(self, telemetry=None, service: str = "deepflow-querier",
                 shard_id: int = 0, sink=None) -> None:
        self.service = service
        self.shard_id = shard_id
        self.sink = sink
        self._hop = (telemetry.hop("query.trace") if telemetry is not None
                     else None)
        self._lock = threading.Lock()
        self._pending: list[Span] = []
        self._pending_since = 0.0
        # finished trace buffers queued by _complete (lock-free append
        # on the query thread) until a reader drains them
        self._completed: list[_TraceBuf] = []
        # hop-ledger deltas accumulated at drain time and synced into
        # the telemetry hop at flush/snapshot — hop.account takes its
        # own lock and feeds a histogram, which is too much work to pay
        # per query against the <2% overhead gate
        self._led = {"emitted": 0, "sampled_out": 0, "overflow": 0}
        self._led_wait: list[tuple[int, int]] = []
        self.stats = {"traces": 0, "spans": 0, "written": 0,
                      "sampled_out": 0, "overflow": 0, "flushes": 0,
                      "sink_errors": 0}

    # -- trace lifecycle -----------------------------------------------------

    def start_trace(self, name: str, trace_id: str | None = None,
                    capture: bool = False, **attrs) -> Span:
        """Open the ROOT span of a new trace on this thread.  Use as a
        context manager; on exit the whole trace is accounted+flushed.
        ``capture=True`` (EXPLAIN ANALYZE) keeps spans on the buf for the
        caller regardless of the sampling verdict."""
        if not _enabled() and not capture:
            return _NULL_SPAN  # type: ignore[return-value]
        if trace_id:
            tid = trace_id
        else:
            hi, lo = next(_ids), next(_ids)
            tid = "%016x%016x" % (hi & 0xFFFFFFFFFFFFFFFF,
                                  lo & 0xFFFFFFFFFFFFFFFF)
        # head-sampling verdict is LAZY (None): computed at drain time,
        # or at first wire export for federated fan-out — two env reads
        # and a hash the bulk local path never pays inline
        buf = _TraceBuf(self, tid, None, capture)
        root = _RootSpan(buf, name, "", attrs)
        buf.root = root
        return root

    def adopt(self, ctx, name: str, **attrs) -> Span:
        """Shard-side join of a propagated trace context (the ``qtrace``
        dict off the scatter body).  Returns a root-like span parented
        under the coordinator's scatter span; sampling verdict is taken
        from the coordinator so the whole trace lives or dies together."""
        if not isinstance(ctx, dict) or not ctx.get("tid"):
            return _NULL_SPAN  # type: ignore[return-value]
        if not _enabled():
            return _NULL_SPAN  # type: ignore[return-value]
        buf = _TraceBuf(self, str(ctx["tid"]), bool(ctx.get("sampled", True)),
                        False)
        root = _RootSpan(buf, name, str(ctx.get("sid", "")), attrs)
        buf.root = root
        return root

    def _complete(self, buf: _TraceBuf) -> None:
        """Hand a finished trace over.  Runs on the query thread at root
        exit, so it does the absolute minimum: one lock-free append onto
        the completed queue.  The sampling verdict, stats, ledger and
        pending-buffer work all run at drain time -- off the request
        path unless the caller explicitly wants read-your-writes."""
        buf._done = True
        completed = self._completed
        completed.append(buf)
        if buf.capture:
            # EXPLAIN ANALYZE wants read-your-writes: flush inline
            self.flush()
        elif len(completed) >= _DRAIN_TRACES:
            # the sink write is a columnar append (dict growth, chunk
            # seal) -- paying it inside a query request shows up in the
            # <2% overhead gate, so periodic flushes run off-thread
            threading.Thread(target=self.flush, daemon=True,
                             name="df-qtrace-flush").start()

    def _drain_locked(self) -> None:
        """Process completed trace buffers: head/tail sampling verdict,
        stats, ledger deltas, pending extension.  Caller holds
        ``self._lock``; every reader (flush/snapshot/pending_spans)
        drains first, so the visible state is always consistent."""
        if not self._completed:
            return
        batch, self._completed = self._completed, []
        st = self.stats
        led = self._led
        for buf in batch:
            root = buf.root
            spans = buf.spans
            overflow = buf.overflow
            n = len(spans)
            if buf.sampled is None:
                buf.sampled = _head_keep(buf.trace_id, _sample_n())
            # tail upgrade: slow or errored traces are always kept;
            # capture (EXPLAIN ANALYZE) is an explicit request, never
            # sampled out
            keep = buf.sampled or buf.capture
            if root is not None and not keep:
                if (root.status != "ok"
                        or root.end_ns - root.start_ns >= _slow_ns()):
                    keep = True
            st["traces"] += 1
            st["spans"] += n
            led["emitted"] += n + overflow
            if overflow:
                st["overflow"] += overflow
                led["overflow"] += overflow
            if not keep:
                st["sampled_out"] += n
                led["sampled_out"] += n
                continue
            # kept spans are in_flight until the sink write delivers
            # them: in_flight on the ledger == the pending buffer,
            # exactly like a frame hop's queue
            self._pending.extend(spans)
            if root is not None:
                # wait observes the root's duration per emitted span --
                # how long spans sat on the trace before heading to the
                # sink queue
                self._led_wait.append(
                    (root.end_ns - root.start_ns, n + overflow))
            if not self._pending_since:
                self._pending_since = time.monotonic()

    def _sync_hop_locked(self) -> None:
        """Push accumulated ledger deltas into the telemetry hop.
        Caller holds ``self._lock`` — everyone reading the hop goes
        through flush() or snapshot(), so the hop is always consistent
        with the pending buffer at those points."""
        hop = self._hop
        if hop is None:
            return
        led = self._led
        if led["emitted"]:
            hop.account(emitted=led["emitted"])
            led["emitted"] = 0
        if led["sampled_out"]:
            hop.account(dropped=led["sampled_out"], reason="sampled_out")
            led["sampled_out"] = 0
        if led["overflow"]:
            hop.account(dropped=led["overflow"], reason="overflow")
            led["overflow"] = 0
        if self._led_wait:
            for wait_ns, weight in self._led_wait:
                hop.observe_wait(wait_ns, weight)
            self._led_wait = []

    # -- sink ----------------------------------------------------------------

    def flush(self) -> int:
        """Push pending span dicts to the sink.  Returns rows written."""
        with self._lock:
            self._drain_locked()
            self._sync_hop_locked()
            if not self._pending or self.sink is None:
                return 0
            batch, self._pending = self._pending, []
            self._pending_since = 0.0
        try:
            self.sink([s.to_dict(s._buf) for s in batch])
        except Exception:
            log.exception("query_trace sink failed (%d spans)", len(batch))
            with self._lock:
                self.stats["sink_errors"] += 1
            if self._hop is not None:
                self._hop.account(dropped=len(batch), reason="sink_error")
            return 0
        with self._lock:
            self.stats["written"] += len(batch)
            self.stats["flushes"] += 1
        if self._hop is not None:
            self._hop.account(delivered=len(batch))
        return len(batch)

    def pending_spans(self, trace_id: str) -> list[dict]:
        """Read-your-writes: span dicts kept but not yet flushed to the
        table (mirrors trace_trees.pending_spans for flow traces)."""
        with self._lock:
            self._drain_locked()
            kept = [s for s in self._pending
                    if s._buf.trace_id == trace_id]
        return [s.to_dict(s._buf) for s in kept]

    def snapshot(self) -> dict:
        with self._lock:
            self._drain_locked()
            self._sync_hop_locked()
            out = dict(self.stats)
            out["pending"] = len(self._pending)
        out["enabled"] = _enabled()
        out["sample_n"] = _sample_n()
        if self._hop is not None:
            out["ledger"] = self._hop.snapshot()
        return out


def rows_from_spans(spans: list[dict]) -> list[dict]:
    """Span dicts -> deepflow_system.query_trace rows (missing universal
    tags take the table defaults)."""
    rows = []
    for d in spans:
        rows.append({
            "time": int(d.get("start_ns", 0)),
            "trace_id": str(d.get("trace_id", "")),
            "span_id": str(d.get("span_id", "")),
            "parent_span_id": str(d.get("parent_span_id", "")),
            "name": str(d.get("name", "")),
            "service": str(d.get("service", "")),
            "duration_ns": int(d.get("duration_ns", 0)),
            "cpu_ns": int(d.get("cpu_ns", 0)),
            "status": str(d.get("status", "ok")),
            "attr_json": json.dumps(d.get("attrs") or {}, sort_keys=True,
                                    default=str),
        })
    return rows


def spans_from_rows(rows) -> list[dict]:
    """Inverse of ``rows_from_spans`` for the Tempo read path: table row
    dicts -> span dicts in the shape query/tracing.py assembles."""
    out = []
    for r in rows:
        try:
            attrs = json.loads(r.get("attr_json") or "{}")
        except ValueError:
            attrs = {}
        start = int(r.get("time", 0))
        out.append({
            "trace_id": str(r.get("trace_id", "")),
            "span_id": str(r.get("span_id", "")),
            "parent_span_id": str(r.get("parent_span_id", "")),
            "name": str(r.get("name", "")),
            "service": str(r.get("service", "")),
            "start_ns": start,
            "end_ns": start + int(r.get("duration_ns", 0)),
            "duration_ns": int(r.get("duration_ns", 0)),
            "cpu_ns": int(r.get("cpu_ns", 0)),
            "status": str(r.get("status", "ok")),
            "kind": "query",
            "attrs": attrs,
        })
    return out


# -- module-level API (reads the thread-local active buffer) -----------------

def active() -> bool:
    return getattr(_tls, "buf", None) is not None


def span(name: str, **attrs):
    """Child span under the current thread's open span; no-op singleton
    when no trace is active on this thread."""
    buf = getattr(_tls, "buf", None)
    if buf is None:
        return _NULL_SPAN
    parent = getattr(_tls, "span", None)
    pid = parent.span_id if isinstance(parent, Span) else (
        buf.root.span_id if buf.root is not None else "")
    return Span(buf, name, pid, attrs)


def annotate(**attrs) -> None:
    cur = getattr(_tls, "span", None)
    if isinstance(cur, Span):
        cur.attrs.update(attrs)


def bump(key: str, n: int = 1) -> None:
    cur = getattr(_tls, "span", None)
    if isinstance(cur, Span):
        cur.attrs[key] = cur.attrs.get(key, 0) + n


def current_buf():
    """Opaque capture handle for cross-thread propagation (see
    ``use_buf``); None when no trace is active."""
    return getattr(_tls, "buf", None)


def current_span_id() -> str:
    cur = getattr(_tls, "span", None)
    if isinstance(cur, Span):
        return cur.span_id
    buf = getattr(_tls, "buf", None)
    if buf is not None and buf.root is not None:
        return buf.root.span_id
    return ""


def ctx_for_wire() -> dict | None:
    """Context dict to ship in a scatter body: the receiving shard
    adopts it so its spans stitch under the coordinator's."""
    buf = getattr(_tls, "buf", None)
    if buf is None:
        return None
    if buf.sampled is None:
        # fan-out forces the head verdict now so every shard of this
        # trace lives or dies together (local traces decide at drain)
        buf.sampled = _head_keep(buf.trace_id, _sample_n())
    return {"tid": buf.trace_id, "sid": current_span_id(),
            "sampled": buf.sampled}


class use_buf:
    """Attach a worker thread to a captured trace buffer for the scope
    of one unit of work (morsel scan, fan-out RPC).  ``parent_sid``
    parents the worker's spans under the span open at submit time."""

    __slots__ = ("buf", "parent_sid", "_prev_buf", "_prev_span")

    def __init__(self, buf, parent_sid: str = "") -> None:
        self.buf = buf
        self.parent_sid = parent_sid

    def __enter__(self) -> "use_buf":
        self._prev_buf = getattr(_tls, "buf", None)
        self._prev_span = getattr(_tls, "span", None)
        _tls.buf = self.buf
        # synthesize an anchor so span() parents under parent_sid: the
        # anchor itself is never finished/recorded
        if self.buf is not None and self.parent_sid:
            anchor = Span.__new__(Span)
            anchor.span_id = self.parent_sid
            anchor.attrs = {}  # annotate()/bump() land harmlessly here
            _tls.span = anchor
        else:
            _tls.span = None
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        _tls.buf = self._prev_buf
        _tls.span = self._prev_span
