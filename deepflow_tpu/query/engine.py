"""Vectorized DF-SQL executor over ColumnarTables.

Reference analog: server/querier/engine/clickhouse/clickhouse.go:184
(CHEngine.ExecuteQuery) — but instead of translating to ClickHouse SQL we
compile the AST to numpy ops, with SmartEncoding dictionary translation
pushed down onto the (small) dictionaries rather than the rows.

Execution stays dictionary-ENCODED end to end (the ClickHouse
LowCardinality discipline): grouping, HAVING, ORDER BY and LIMIT all run
on int columns — grouping through the native hash-group kernel
(native/qexec.cpp, numpy lexsort fallback, DF_NO_NATIVE kill-switch) and
ORDER BY through collation ranks computed once per (small) dictionary.
Only the final top-K rows are decoded to strings. DF_QUERY_ENCODED=0
selects the legacy decode-then-Python-sort path for A/B parity checks.
"""

from __future__ import annotations

import os
import re
import threading
import time
from dataclasses import dataclass

import numpy as np

from deepflow_tpu import native
from deepflow_tpu.query import pool as qpool
from deepflow_tpu.query import qtrace
from deepflow_tpu.query import sql as S
from deepflow_tpu.query.costmodel import KernelCostModel
from deepflow_tpu.store.table import ColumnarTable

# Shared group-kernel cost model: native hash-group vs numpy lexsort.
# Initial overheads seed the choice before observations exist (ctypes
# marshalling makes the native call more expensive per invocation).
_COST = KernelCostModel(overhead_ns={"native": 15_000.0, "numpy": 2_000.0})

# Serial vs morsel-parallel scan degree. The parallel kernel pays pool
# dispatch plus a partial-combine pass, seeded here as fixed overhead so
# small queries keep choosing the serial plan before any observation
# exists; the coefficients are then learned per machine like _COST's.
_DEGREE = KernelCostModel(kernels=("serial", "parallel"),
                          overhead_ns={"parallel": 500_000.0})

_MORSEL_ROWS = 1 << 16  # fixed-row morsel size (docs/QUERY.md)


def _morsel_rows() -> int:
    env = os.environ.get("DF_QUERY_MORSEL_ROWS", "").strip()
    if env:
        try:
            return max(256, int(env))
        except ValueError:
            pass
    return _MORSEL_ROWS


@dataclass
class QueryResult:
    columns: list[str]
    values: list[list]

    def to_dict(self) -> dict:
        return {"columns": self.columns, "values": self.values}

    def column(self, name: str) -> list:
        return [row[self.columns.index(name)] for row in self.values]


class QueryError(Exception):
    pass


@dataclass
class _Val:
    """Evaluated vector + decode metadata."""
    arr: np.ndarray
    kind: str = "num"           # num | str | enum | bool
    dict_ = None                # Dictionary when kind == 'str'
    labels: tuple = ()          # when kind == 'enum'
    unit: str | None = None     # 'ns' | 's' for time columns

    def decoded(self) -> list:
        if self.kind == "str":
            return self.dict_.decode_many(self.arr)
        if self.kind == "enum":
            lab = self.labels
            return [lab[i] for i in self.arr.tolist()]
        if self.kind == "bool":
            return self.arr.astype(bool).tolist()
        # 'obj': python values (CASE branches mixing literals)
        return self.arr.tolist()


def _col_val(table: ColumnarTable, name: str, arr: np.ndarray) -> _Val:
    spec = table.columns[name]
    if spec.kind == "str":
        v = _Val(arr, "str")
        v.dict_ = table.dicts[name]
        return v
    if spec.kind == "enum":
        return _Val(arr, "enum", labels=spec.enum_values)
    unit = None
    if name in ("time", "start_time", "end_time"):
        unit = "ns" if spec.kind == "u64" else "s"
    return _Val(arr, "num", unit=unit)


def _collect_cols(e, out: set) -> None:
    if isinstance(e, S.Col):
        out.add(e.name)
    elif isinstance(e, S.Func):
        for a in e.args:
            _collect_cols(a, out)
    elif isinstance(e, S.BinOp):
        _collect_cols(e.left, out)
        if not isinstance(e.right, tuple):
            _collect_cols(e.right, out)
    elif isinstance(e, S.Not):
        _collect_cols(e.expr, out)
    elif isinstance(e, S.Case):
        for c, v in e.whens:
            _collect_cols(c, out)
            _collect_cols(v, out)
        if e.default is not None:
            _collect_cols(e.default, out)


def _like_to_pred(pattern: str):
    """SQL LIKE: % and _ are wildcards, everything else literal."""
    parts = []
    for ch in pattern:
        if ch == "%":
            parts.append(".*")
        elif ch == "_":
            parts.append(".")
        else:
            parts.append(re.escape(ch))
    rx = re.compile("^" + "".join(parts) + "$", re.DOTALL)
    return lambda s: rx.match(s) is not None


def _isin(arr: np.ndarray, vals) -> np.ndarray:
    """np.isin, routed through the native hash-set kernel when the column
    is dictionary-id shaped (uint32) and the literal set is pure ints —
    the encoded-predicate fast path for IN / LIKE pushdown."""
    if arr.dtype == np.uint32 and len(arr):
        vl = np.asarray(vals)
        if (vl.ndim == 1 and vl.dtype.kind in "iu" and len(vl)
                and int(vl.min()) >= 0
                and int(vl.max()) <= 0xFFFFFFFF):
            m = native.qx_isin_u32(np.ascontiguousarray(arr),
                                   vl.astype(np.uint32))
            if m is not None:
                return m
    return np.isin(arr, vals)


def _case_select(conds, vals, default, shape) -> _Val:
    """Shared CASE combination for the row-level and aggregate paths.
    All-numeric branches stay float64; any non-numeric branch coerces
    EVERY branch to strings (one consistent dtype — a mixed str/num
    object array would crash GROUP BY/ORDER BY comparisons)."""
    branch_vals = vals + ([default] if default is not None else [])
    if all(v.kind == "num" and v.arr.dtype.kind in "fiub"
           for v in branch_vals):
        choices = [np.broadcast_to(v.arr.astype(np.float64), shape)
                   for v in vals]
        dflt = (default.arr.astype(np.float64) if default is not None
                else np.nan)
        if getattr(dflt, "ndim", 0):
            dflt = np.broadcast_to(dflt, shape)
        return _Val(np.select(conds, choices, default=dflt))

    def as_str(v: _Val):
        dec = v.decoded()
        if not isinstance(dec, list):
            return dec if isinstance(dec, str) else str(dec)
        return np.asarray([x if isinstance(x, str) else str(x)
                           for x in dec], dtype=object)
    choices = [np.broadcast_to(np.asarray(as_str(v), dtype=object), shape)
               for v in vals]
    dflt = as_str(default) if default is not None else ""
    if not isinstance(dflt, str) and getattr(dflt, "ndim", 0):
        dflt = np.broadcast_to(dflt, shape)
    return _Val(np.select(conds, choices, default=dflt), "obj")


class _Env:
    """Column arrays for one evaluation scope."""

    def __init__(self, table: ColumnarTable, cols: dict[str, np.ndarray]):
        self.table = table
        self.cols = cols

    def eval(self, e) -> _Val:
        if isinstance(e, S.Lit):
            return _Val(np.asarray(e.value), "num")
        if isinstance(e, S.Col):
            if e.name not in self.cols:
                raise QueryError(f"unknown column {e.name!r} in "
                                 f"{self.table.name}")
            return _col_val(self.table, e.name, self.cols[e.name])
        if isinstance(e, S.Not):
            v = self.eval(e.expr)
            return _Val(~v.arr.astype(bool), "bool")
        if isinstance(e, S.Func):
            return self._eval_func(e)
        if isinstance(e, S.BinOp):
            return self._eval_binop(e)
        if isinstance(e, S.Case):
            return self._eval_case(e)
        if isinstance(e, S.Star):
            raise QueryError("* only valid inside Count()")
        raise QueryError(f"cannot evaluate {e!r}")

    def _eval_case(self, e: "S.Case") -> _Val:
        conds = [self.eval(c).arr.astype(bool) for c, _ in e.whens]
        vals = [self.eval(v) for _, v in e.whens]
        default = self.eval(e.default) if e.default is not None else None
        return _case_select(conds, vals, default, conds[0].shape)

    def _eval_func(self, e: S.Func) -> _Val:
        if e.name in S.AGG_FUNCS:
            raise QueryError(f"aggregate {e.name} outside aggregation")
        if e.name == "TIME":
            if len(e.args) != 2:
                raise QueryError("time(col, interval_s) takes 2 args")
            v = self.eval(e.args[0])
            iv = self.eval(e.args[1]).arr
            interval = int(iv)
            t = v.arr.astype(np.int64)
            if v.unit == "ns":
                t = t // 1_000_000_000
            return _Val((t // interval) * interval, "num", unit="s")
        raise QueryError(f"unknown function {e.name}")

    def _eval_binop(self, e: S.BinOp) -> _Val:
        op = e.op
        if op in ("AND", "OR"):
            lv = self.eval(e.left).arr.astype(bool)
            rv = self.eval(e.right).arr.astype(bool)
            return _Val(lv & rv if op == "AND" else lv | rv, "bool")
        if op == "IN":
            lv = self.eval(e.left)
            vals = [self._coerce_lit(lv, lit.value) for lit in e.right]
            return _Val(_isin(lv.arr, vals), "bool")
        if op == "LIKE":
            lv = self.eval(e.left)
            if lv.kind == "str":
                ids = lv.dict_.match_ids(_like_to_pred(e.right.value))
                return _Val(_isin(lv.arr, ids), "bool")
            if lv.kind == "enum":
                pred = _like_to_pred(e.right.value)
                ids = [i for i, s in enumerate(lv.labels) if pred(s)]
                return _Val(np.isin(lv.arr, ids), "bool")
            raise QueryError("LIKE requires a string column")
        lv = self.eval(e.left)
        if op in ("=", "!=", "<", "<=", ">", ">="):
            rv_raw = e.right
            if isinstance(rv_raw, S.Lit) and isinstance(rv_raw.value, str):
                if op not in ("=", "!="):
                    # dictionary codes reflect insertion order, not
                    # collation — resolve the predicate over the (small)
                    # dictionary in STRING space, then membership-test
                    # the ids (same pushdown shape as LIKE). The v2
                    # zstr zones prune segments for these before any
                    # column decodes.
                    val = rv_raw.value
                    pred = {"<": lambda s: s < val,
                            "<=": lambda s: s <= val,
                            ">": lambda s: s > val,
                            ">=": lambda s: s >= val}[op]
                    if lv.kind == "str":
                        ids = lv.dict_.match_ids(pred)
                        return _Val(_isin(lv.arr, ids), "bool")
                    if lv.kind == "enum":
                        ids = [i for i, s in enumerate(lv.labels)
                               if pred(s)]
                        return _Val(np.isin(lv.arr, ids), "bool")
                    raise QueryError(
                        "ordered comparison against a string requires "
                        "a string or enum column")
                code = self._coerce_lit(lv, rv_raw.value)
                l, r = lv.arr, np.asarray(code)
            else:
                rv = self.eval(rv_raw)
                l, r = self._align_encoded(lv, rv, op)
            res = {"=": l.__eq__, "!=": l.__ne__, "<": l.__lt__,
                   "<=": l.__le__, ">": l.__gt__, ">=": l.__ge__}[op](r)
            return _Val(res, "bool")
        # arithmetic
        rv = self.eval(e.right)
        l = lv.arr.astype(np.float64)
        r = rv.arr.astype(np.float64)
        if op == "+":
            return _Val(l + r)
        if op == "-":
            return _Val(l - r)
        if op == "*":
            return _Val(l * r)
        if op == "/":
            with np.errstate(divide="ignore", invalid="ignore"):
                out = np.where(r != 0, l / np.where(r == 0, 1, r), 0.0)
            return _Val(out)
        raise QueryError(f"unknown op {op}")

    def _align_encoded(self, lv: _Val, rv: _Val, op: str):
        """Align two columns for comparison. Dictionary-encoded codes from
        *different* dictionaries are not comparable — remap the right side's
        ids into the left dictionary via the (small) unique-id set."""
        enc_l = lv.kind in ("str", "enum")
        enc_r = rv.kind in ("str", "enum")
        if not (enc_l or enc_r):
            return lv.arr, rv.arr
        if op not in ("=", "!="):
            raise QueryError(
                "ordered comparison between string columns is not supported")
        if lv.kind == "str" and rv.kind == "str":
            if lv.dict_ is rv.dict_:
                return lv.arr, rv.arr
            uniq = np.unique(rv.arr)
            sentinel = np.uint32(0xFFFFFFFF)
            remap = {int(u): (lambda s: np.uint32(s) if s is not None
                              else sentinel)(lv.dict_.lookup(rv.dict_.decode(int(u))))
                     for u in uniq}
            mapped = np.array([remap[int(c)] for c in rv.arr],
                              dtype=np.uint32)
            return lv.arr, mapped
        if lv.kind == "enum" and rv.kind == "enum":
            if lv.labels == rv.labels:
                return lv.arr, rv.arr
            remap = {i: (rv.labels.index(s) if s in rv.labels else 0xFFFF)
                     for i, s in enumerate(lv.labels)}
            mapped = np.array([remap[int(c)] for c in lv.arr],
                              dtype=np.uint16)
            return mapped, rv.arr
        raise QueryError(
            f"cannot compare {lv.kind} column with {rv.kind} column")

    def _coerce_lit(self, lv: _Val, value):
        """Translate a literal to the column's encoded space."""
        if lv.kind == "str" and isinstance(value, str):
            sid = lv.dict_.lookup(value)
            return np.uint32(sid) if sid is not None else np.uint32(0xFFFFFFFF)
        if lv.kind == "enum" and isinstance(value, str):
            try:
                return np.uint16(lv.labels.index(value))
            except ValueError:
                return np.uint16(0xFFFF)
        return value


# -- aggregation ------------------------------------------------------------

_SEG_OPS = {"SUM": 0, "MIN": 1, "MAX": 2}


def _group_reduce(name: str, af: np.ndarray, order: np.ndarray,
                  bounds_full: np.ndarray) -> np.ndarray:
    """Fused gather + segmented reduce over float64 values. The native
    kernel (df_qx_agg_f64) accumulates sequentially within each group —
    exactly what ufunc.reduceat over the gathered array does — so the
    two paths are bit-identical, and the native one releases the GIL,
    which is where the morsel pool's parallelism actually comes from."""
    if len(bounds_full) <= 1:
        return np.empty(0, dtype=np.float64)
    out = native.qx_agg_f64(np.ascontiguousarray(af, dtype=np.float64),
                            order, bounds_full, _SEG_OPS[name])
    if out is not None:
        return out
    g = af.astype(np.float64)[order]
    ufn = {"SUM": np.add, "MIN": np.minimum, "MAX": np.maximum}[name]
    return ufn.reduceat(g, bounds_full[:-1])


def _agg_eval(e, env: _Env, order: np.ndarray, bounds: np.ndarray) -> _Val:
    """Evaluate expr containing aggregates; per-group output.

    order: row permutation grouping rows; bounds: group start indices into
    order (len == n_groups, implicit end at len(order)).
    """
    starts = bounds
    ends = np.append(bounds[1:], len(order))
    if isinstance(e, S.Func) and e.name in S.AGG_FUNCS:
        if e.distinct and e.name != "COUNT":
            raise QueryError(
                f"DISTINCT is only supported in Count(), not {e.name}")
        if e.name == "COUNT" and e.distinct:
            if len(e.args) != 1 or isinstance(e.args[0], S.Star):
                raise QueryError(
                    "COUNT(DISTINCT) takes exactly one column")
            v = env.eval(e.args[0])
            a = v.arr[order]  # encoded ids / numerics both hash fine
            if not len(a):
                return _Val(np.zeros(len(starts), dtype=np.float64))
            # one lexsort total instead of one np.unique per group:
            # sort (group, value), count within-group value changes
            grp = np.repeat(np.arange(len(starts)), ends - starts)
            idx = np.lexsort((a, grp))
            sa, sg = a[idx], grp[idx]
            fresh = np.append(True, (sa[1:] != sa[:-1]) |
                              (sg[1:] != sg[:-1]))
            return _Val(np.add.reduceat(
                fresh.astype(np.float64), starts))
        if e.name == "COUNT":
            return _Val((ends - starts).astype(np.float64))
        arg = e.args[0] if e.args else S.Star()
        if isinstance(arg, S.Star):
            return _Val((ends - starts).astype(np.float64))
        v = env.eval(arg)
        if v.kind in ("str", "enum", "obj") and e.name != "LAST":
            raise QueryError(
                f"{e.name} over string column {S.expr_name(arg)!r}")
        af = v.arr.astype(np.float64)
        bounds_full = np.append(starts, len(order))
        if e.name == "SUM":
            return _Val(_group_reduce("SUM", af, order, bounds_full))
        if e.name == "AVG":
            s = _group_reduce("SUM", af, order, bounds_full)
            n = (ends - starts)
            return _Val(s / np.maximum(n, 1))
        if e.name == "MIN":
            return _Val(_group_reduce("MIN", af, order, bounds_full))
        if e.name == "MAX":
            return _Val(_group_reduce("MAX", af, order, bounds_full))
        a = af[order]
        if e.name == "LAST":
            out = a[ends - 1] if len(a) else a
            v2 = _Val(out, v.kind, labels=v.labels)
            v2.dict_ = v.dict_
            if v.kind in ("str", "enum"):
                v2.arr = v.arr[order][ends - 1] if len(a) else v.arr
            return v2
        if e.name == "PERCENTILE":
            if len(e.args) != 2:
                raise QueryError("Percentile(col, p) takes 2 args")
            p = float(env.eval(e.args[1]).arr)
            out = np.empty(len(starts), dtype=np.float64)
            for i, (s0, e0) in enumerate(zip(starts, ends)):
                out[i] = np.percentile(a[s0:e0], p) if e0 > s0 else 0.0
            return _Val(out)
        raise QueryError(f"unknown aggregate {e.name}")
    if isinstance(e, S.Not):
        v = _agg_eval(e.expr, env, order, bounds)
        return _Val(~v.arr.astype(bool), "bool")
    if isinstance(e, S.BinOp):
        # logical / comparison ops appear here via HAVING
        if e.op in ("AND", "OR"):
            lv = _agg_eval(e.left, env, order, bounds).arr.astype(bool)
            rv = _agg_eval(e.right, env, order, bounds).arr.astype(bool)
            return _Val(lv & rv if e.op == "AND" else lv | rv, "bool")
        if e.op == "IN":
            lv = _agg_eval(e.left, env, order, bounds)
            vals = [lit.value for lit in e.right]
            if lv.kind in ("str", "enum"):
                dec = np.asarray(lv.decoded(), dtype=object)
                return _Val(np.isin(dec, vals), "bool")
            return _Val(np.isin(lv.arr, vals), "bool")
        lv = _agg_eval(e.left, env, order, bounds)
        rv = _agg_eval(e.right, env, order, bounds)
        if e.op in ("=", "!=", "<", "<=", ">", ">="):
            if lv.kind in ("str", "enum") or rv.kind in ("str", "enum"):
                # HAVING over group-key strings: compare decoded values
                l = np.asarray(lv.decoded(), dtype=object)
                r = (np.asarray(rv.decoded(), dtype=object)
                     if rv.kind in ("str", "enum") else rv.arr)
            else:
                l, r = lv.arr, rv.arr
            res = {"=": l.__eq__, "!=": l.__ne__, "<": l.__lt__,
                   "<=": l.__le__, ">": l.__gt__, ">=": l.__ge__}[e.op](r)
            return _Val(np.asarray(res), "bool")
        l, r = lv.arr.astype(np.float64), rv.arr.astype(np.float64)
        if e.op == "+":
            return _Val(l + r)
        if e.op == "-":
            return _Val(l - r)
        if e.op == "*":
            return _Val(l * r)
        if e.op == "/":
            with np.errstate(divide="ignore", invalid="ignore"):
                return _Val(np.where(r != 0, l / np.where(r == 0, 1, r), 0.0))
        raise QueryError(f"op {e.op} not valid over aggregates")
    if isinstance(e, S.Lit):
        return _Val(np.asarray(e.value))
    if isinstance(e, S.Case) and S.contains_agg(e):
        # CASE over aggregates (per-group labels from per-group stats)
        conds = [_agg_eval(c, env, order, bounds).arr.astype(bool)
                 for c, _ in e.whens]
        vals = [_agg_eval(v, env, order, bounds) for _, v in e.whens]
        default = (_agg_eval(e.default, env, order, bounds)
                   if e.default is not None else None)
        return _case_select(conds, vals, default, (len(bounds),))
    if isinstance(e, (S.Col, S.Func, S.Case)):
        # group-key expression: first value per group
        v = env.eval(e)
        out = _Val(v.arr[order][bounds], v.kind, labels=v.labels, unit=v.unit)
        out.dict_ = v.dict_
        return out
    raise QueryError(f"cannot aggregate {e!r}")


def _normalize(table: ColumnarTable, query: S.Select) -> S.Select:
    """Shared front half of execute(): derived-metric rewrite + GROUP BY
    alias substitution. Runs identically on every shard AND on the merge
    coordinator, so both sides derive the same partial-result layout
    from the same SQL text."""
    # derived metrics (Avg(rtt) -> Sum(rtt_sum)/Sum(rtt_count)) before
    # column validation, so the virtual names never hit the store.
    # Display names and ORDER BY matching use the PRE-rewrite expressions:
    # the user asked for Avg(rtt), not the implementation ratio.
    from deepflow_tpu.query import catalog as _catalog
    try:
        tcols = set(table.columns)
        # alias defaults to the PRE-rewrite display name, which also lets
        # ORDER BY Avg(rtt) match its SELECT item by name below
        query_items = [
            S.SelectItem(_catalog.rewrite_derived(i.expr, table.name, tcols),
                         i.alias or S.expr_name(i.expr))
            for i in query.items]
        having = (_catalog.rewrite_derived(query.having, table.name, tcols)
                  if query.having is not None else None)
    except _catalog._DerivedError as e:
        raise QueryError(str(e)) from None
    # GROUP BY <alias>: substitute the SELECT item's expression (the
    # alias names no real column)
    alias_map = {i.alias: i.expr for i in query_items if i.alias}
    group_by = [
        alias_map[g.name]
        if isinstance(g, S.Col) and g.name not in table.columns
        and g.name in alias_map else g
        for g in query.group_by]
    return S.Select(items=query_items, table=query.table,
                    where=query.where, group_by=group_by,
                    having=having, order_by=query.order_by,
                    limit=query.limit)


# -- zone-map segment pruning ------------------------------------------------
#
# Segment footers carry per-column [zmin, zmax] over the ENCODED values
# (store/segment.py). A WHERE clause is lowered to per-column closed
# intervals over that same encoded space — string literals via
# dictionary lookup, enum labels via index — and a segment whose zone is
# disjoint from any interval provably holds no matching row, so its
# mmap is never touched. Only top-level AND conjuncts of the forms
# `col <op> literal` / `col IN (...)` yield intervals; anything else
# simply doesn't prune, which is always sound.

_SCAN_LOCK = threading.Lock()
_SCAN_STATS = {"scanned_segments": 0, "pruned_segments": 0,
               "bloom_checked": 0, "bloom_pruned": 0}
_SCAN_HOP = None


def set_scan_telemetry(telemetry) -> None:
    """Wire the query.scan hop ledger (emitted=candidate segments,
    delivered=scanned, dropped=pruned): pruning must be observable from
    /v1/health, never inferred from timings."""
    global _SCAN_HOP
    _SCAN_HOP = telemetry.hop("query.scan") if telemetry else None


def scan_stats() -> dict:
    with _SCAN_LOCK:
        return dict(_SCAN_STATS)


def _note_scan(candidates: int, pruned: int, bloom_checked: int = 0,
               bloom_pruned: int = 0) -> None:
    if not candidates:
        return
    scanned = candidates - pruned - bloom_pruned
    with _SCAN_LOCK:
        _SCAN_STATS["scanned_segments"] += scanned
        _SCAN_STATS["pruned_segments"] += pruned
        _SCAN_STATS["bloom_checked"] += bloom_checked
        _SCAN_STATS["bloom_pruned"] += bloom_pruned
    hop = _SCAN_HOP
    if hop is not None:
        # two reasons, one conserved ledger: emitted == delivered +
        # dropped[pruned] + dropped[bloom_pruned] per scan
        hop.account(emitted=candidates, delivered=scanned,
                    dropped=pruned, reason="pruned")
        if bloom_pruned:
            hop.account(dropped=bloom_pruned, reason="bloom_pruned")


def split_conjuncts(e) -> list:
    """Flatten top-level ANDs into conjuncts (shared with the rollup
    datasource's time-window classifier)."""
    if isinstance(e, S.BinOp) and e.op == "AND":
        return split_conjuncts(e.left) + split_conjuncts(e.right)
    return [e]


_NO_ROW = object()   # literal provably matches no row (absent string)
_NEVER_CON = (None, 0, 0)  # constraint entry: WHERE matches nothing


def _zone_coerce(table: ColumnarTable, col: str, value):
    """Literal -> the column's encoded number space. None = not
    comparable against zones; _NO_ROW = provably matches no row. Ints
    stay ints (u64 timestamps exceed float53 precision — a rounded
    bound could prune a segment that holds matching rows)."""
    spec = table.columns[col]
    if isinstance(value, str):
        if spec.kind == "str":
            sid = table.dicts[col].lookup(value)
            return _NO_ROW if sid is None else int(sid)
        if spec.kind == "enum":
            try:
                return spec.enum_values.index(value)
            except ValueError:
                return _NO_ROW
        return None
    if isinstance(value, bool):
        return int(value)
    if isinstance(value, (int, float)):
        # numeric literals compare against encoded ids numerically in
        # _Env._coerce_lit, so the raw value is the encoded-space bound
        return value
    return None


def _zone_constraints(table: ColumnarTable, where) -> list[tuple]:
    """-> [(col, lo, hi)] closed-interval NECESSARY conditions; lo/hi
    None = unbounded on that side; col None = the WHERE provably
    matches nothing (equality against an absent dictionary string).
    `<` / `>` widen to `<=` / `>=` — conservative, still sound."""
    cons: list[tuple] = []
    for c in split_conjuncts(where):
        if not (isinstance(c, S.BinOp) and isinstance(c.left, S.Col)
                and c.left.name in table.columns):
            continue
        col = c.left.name
        if c.op == "IN" and isinstance(c.right, tuple) and c.right:
            vals, dead, skip = [], False, False
            for lit in c.right:
                if not isinstance(lit, S.Lit):
                    skip = True
                    break
                v = _zone_coerce(table, col, lit.value)
                if v is None:
                    skip = True
                    break
                if v is _NO_ROW:
                    dead = True
                else:
                    vals.append(v)
            if skip:
                continue
            if vals:
                cons.append((col, min(vals), max(vals)))
            elif dead:
                cons.append(_NEVER_CON)
            continue
        if (c.op not in ("=", "<", "<=", ">", ">=")
                or not isinstance(c.right, S.Lit)):
            continue
        if (c.op != "=" and isinstance(c.right.value, str)
                and table.columns[col].kind in ("str", "enum")):
            # ordered string predicates live in COLLATION order;
            # dictionary/enum ids reflect insertion order, so an
            # id-space interval here would prune segments that DO hold
            # matching rows. String-order pruning happens against the
            # v2 zstr index in _str_pruned instead.
            continue
        v = _zone_coerce(table, col, c.right.value)
        if v is None:
            continue
        if v is _NO_ROW:
            if c.op == "=":
                cons.append(_NEVER_CON)
            continue
        if c.op == "=":
            cons.append((col, v, v))
        elif c.op in ("<", "<="):
            cons.append((col, None, v))
        else:
            cons.append((col, v, None))
    return cons


def _index_constraints(table: ColumnarTable, where) -> tuple[list, list]:
    """Skip-index NECESSARY conditions from top-level AND conjuncts:

    -> (idcons, strcons) where idcons is [(col, [encoded ids])] from
    `col = 'lit'` / `col IN (...)` over dictionary/enum columns (checked
    against the segment's inline id list or bloom filter) and strcons is
    [(col, op, value)] from ordered string predicates over dictionary
    columns (checked against the segment's zstr collation-order zone).
    Anything else contributes nothing, which is always sound."""
    idcons: list[tuple] = []
    strcons: list[tuple] = []
    for c in split_conjuncts(where):
        if not (isinstance(c, S.BinOp) and isinstance(c.left, S.Col)
                and c.left.name in table.columns):
            continue
        col = c.left.name
        spec = table.columns[col]
        if spec.kind not in ("str", "enum"):
            continue
        if c.op == "IN" and isinstance(c.right, tuple) and c.right:
            ids = []
            ok = True
            for lit in c.right:
                if not isinstance(lit, S.Lit):
                    ok = False
                    break
                v = _zone_coerce(table, col, lit.value)
                if v is None or isinstance(v, float):
                    ok = False
                    break
                if v is not _NO_ROW:
                    ids.append(int(v))
            if ok:
                idcons.append((col, ids))
            continue
        if not isinstance(c.right, S.Lit):
            continue
        if c.op == "=":
            v = _zone_coerce(table, col, c.right.value)
            if v is _NO_ROW:
                idcons.append((col, []))
            elif v is not None and not isinstance(v, float):
                idcons.append((col, [int(v)]))
        elif c.op in ("<", "<=", ">", ">=") and spec.kind == "str" \
                and isinstance(c.right.value, str):
            strcons.append((col, c.op, c.right.value))
    return idcons, strcons


def _str_pruned(seg, strcons: list) -> bool:
    """True when the segment's zstr (collation-order) zone proves no row
    satisfies an ordered string predicate. A truncated upper bound is
    stored as None = unbounded, so absence never prunes."""
    for col, op, val in strcons:
        z = seg.str_zone(col)
        if z is None:
            continue
        lo, hi = z
        if op in (">", ">=") and hi is not None:
            if hi < val or (op == ">" and hi <= val):
                return True
        elif op in ("<", "<="):
            if lo > val or (op == "<" and lo >= val):
                return True
    return False


def _zone_pruned(zones: dict | None, cons: list) -> bool:
    """True when the unit provably holds no matching row. Units without
    zones (live RAM chunks, pre-zone segments sans time span) only prune
    on the WHERE-matches-nothing sentinel."""
    for col, lo, hi in cons:
        if col is None:
            return True
        zb = (zones or {}).get(col)
        if zb is None:
            continue
        zmin, zmax = zb
        if (lo is not None and zmax < lo) or \
                (hi is not None and zmin > hi):
            return True
    return False


def _needed_cols(table: ColumnarTable, query: S.Select,
                 extra_cols: set[str] | None = None) -> set[str]:
    """Every store column the query references, validated against the
    schema. extra_cols: additional columns the caller needs (the
    federated LAST merge wants `time` alongside the value)."""
    needed: set[str] = set(extra_cols or ())
    for item in query.items:
        _collect_cols(item.expr, needed)
    for g in query.group_by:
        _collect_cols(g, needed)
    if query.having is not None:
        _collect_cols(query.having, needed)
    aliases = {i.alias for i in query.items if i.alias}
    for e, _ in query.order_by:
        if isinstance(e, S.Col) and e.name in aliases:
            continue  # refers to a SELECT alias, not a table column
        if S.expr_name(e) in aliases:
            continue  # matches a SELECT item (possibly a derived metric)
        _collect_cols(e, needed)
    if query.where is not None:
        _collect_cols(query.where, needed)
    unknown = needed - set(table.columns)
    if unknown:
        raise QueryError(f"unknown columns {sorted(unknown)} in {table.name}")
    return needed


def _chunk_rows(ch) -> int:
    """Row count of a scan chunk WITHOUT decoding any column: segment
    LazyChunks carry .rows; plain RAM dicts pay one len()."""
    rows = getattr(ch, "rows", None)
    if rows is not None:
        return rows
    return len(next(iter(ch.values()))) if ch else 0


# Index-list filtering vs full-mask evaluation. The native kernels win on
# selective predicates (survivors come back as positions, later conjuncts
# touch only them); numpy wins on tiny chunks where ctypes dispatch
# dominates. Seeded overheads keep small scans on numpy until the model
# has real observations for this machine.
_FILT = KernelCostModel(overhead_ns={"native": 15_000.0, "numpy": 1_000.0})

_ORD_PREDS = {
    "<": lambda val: lambda s: s < val,
    "<=": lambda val: lambda s: s <= val,
    ">": lambda val: lambda s: s > val,
    ">=": lambda val: lambda s: s >= val,
}


def _filter_prims(table: ColumnarTable, where) -> list[tuple] | None:
    """Compile the WHERE into filter primitives, or None when any
    conjunct falls outside the primitive forms (the generic mask path
    then evaluates the whole WHERE — never a partial split, so both
    paths always agree).

    Primitive forms, each provably equivalent to its _Env evaluation:
      ("range", col, lo, hi)  — integer column between two in-dtype
                                bounds (= / < / <= / > / >= with an int
                                literal; one-sided ops use dtype min/max)
      ("isin",  col, ids, _)  — dict/enum column id in a resolved set
                                (= / IN / LIKE / ordered string literal,
                                same id resolution as _eval_binop)
      ("never", col, _, _)    — literal provably out of the column's
                                value space: no row matches
    Float literals are NOT compiled: numpy compares int columns to float
    literals in float space, and mirroring that with integer bounds would
    diverge at the float64-precision edge for u64 timestamps."""
    prims: list[tuple] = []
    for c in split_conjuncts(where):
        if not (isinstance(c, S.BinOp) and isinstance(c.left, S.Col)
                and c.left.name in table.columns):
            return None
        col = c.left.name
        spec = table.columns[col]
        if c.op == "IN":
            if spec.kind not in ("str", "enum") \
                    or not isinstance(c.right, tuple):
                return None
            ids = []
            for lit in c.right:
                if not isinstance(lit, S.Lit) \
                        or not isinstance(lit.value, str):
                    return None
                v = _zone_coerce(table, col, lit.value)
                if v is not _NO_ROW:
                    ids.append(int(v))
            prims.append(("isin", col,
                          np.asarray(sorted(set(ids)), dtype=np.uint32),
                          None))
            continue
        if c.op == "LIKE":
            if not (isinstance(c.right, S.Lit)
                    and isinstance(c.right.value, str)):
                return None
            pred = _like_to_pred(c.right.value)
            if spec.kind == "str":
                ids = table.dicts[col].match_ids(pred)
            elif spec.kind == "enum":
                ids = [i for i, s in enumerate(spec.enum_values)
                       if pred(s)]
            else:
                return None
            prims.append(("isin", col, np.asarray(ids, dtype=np.uint32),
                          None))
            continue
        if c.op not in ("=", "<", "<=", ">", ">=") \
                or not isinstance(c.right, S.Lit):
            return None
        val = c.right.value
        if spec.kind in ("str", "enum"):
            if not isinstance(val, str):
                return None
            if c.op == "=":
                v = _zone_coerce(table, col, val)
                ids = [] if v is _NO_ROW else [int(v)]
            else:
                pred = _ORD_PREDS[c.op](val)
                if spec.kind == "str":
                    ids = table.dicts[col].match_ids(pred)
                else:
                    ids = [i for i, s in enumerate(spec.enum_values)
                           if pred(s)]
            prims.append(("isin", col, np.asarray(ids, dtype=np.uint32),
                          None))
            continue
        dt = np.dtype(spec.np_dtype)
        if dt.kind not in "iu":
            return None
        if isinstance(val, bool):
            val = int(val)
        if not isinstance(val, int):
            return None
        info = np.iinfo(dt)
        lo, hi = int(info.min), int(info.max)
        if c.op == "=":
            lo = hi = val
        elif c.op == "<":
            hi = val - 1
        elif c.op == "<=":
            hi = val
        elif c.op == ">":
            lo = val + 1
        else:
            lo = val
        if lo > int(info.max) or hi < int(info.min):
            prims.append(("never", col, 0, 0))
            continue
        lo = max(lo, int(info.min))
        hi = min(hi, int(info.max))
        prims.append(("range", col, lo, hi))
    return prims


def _select_rows(get_col, sz: int, prims: list[tuple]) -> np.ndarray:
    """Ascending survivor positions for an all-primitive WHERE. The
    first primitive selects over the full column; each later one gathers
    only the current survivors and refines (`idx = idx[sub_idx]`), so a
    selective leading conjunct makes the rest near-free — and an empty
    survivor set short-circuits before later columns ever decode.
    Ascending positions make `arr[idx]` byte-identical to `arr[mask]`
    on the generic path. Kernel choice (native index kernels vs numpy
    nonzero) is learned per size class by _FILT."""
    idx = None  # None = every row still alive
    for kind, col, a, b in prims:
        if kind == "never":
            return np.empty(0, dtype=np.uint64)
        if idx is not None and not len(idx):
            return idx
        arr = get_col(col)
        if arr.ndim == 1 and len(arr) and arr.strides[0] == 0:
            # broadcast fill column: one value answers for every row
            one = arr[:1]
            ok = bool((_isin(one, a) if kind == "isin"
                       else (one >= a) & (one <= b))[0])
            if ok:
                continue
            return np.empty(0, dtype=np.uint64)
        n = sz if idx is None else len(idx)
        kern = _FILT.choose(n) if native.available() else "numpy"
        t0 = time.perf_counter_ns()
        if idx is None:
            sub = arr
        else:
            sub = native.qx_gather(arr, idx) if kern == "native" else None
            if sub is None:
                sub = arr[idx]
        out = None
        if kern == "native":
            out = (native.qx_sel_range(sub, a, b) if kind == "range"
                   else native.qx_sel_isin(sub, a))
        if out is None:
            kern = "numpy"
            m = (_isin(sub, a) if kind == "isin"
                 else (sub >= a) & (sub <= b))
            out = np.nonzero(m)[0].astype(np.uint64)
        _FILT.observe(kern, n, time.perf_counter_ns() - t0)
        idx = out if idx is None else idx[out]
    if idx is None:
        idx = np.arange(sz, dtype=np.uint64)
    return idx


def _scan_plan(table: ColumnarTable, query: S.Select) -> list[dict]:
    """One scan's chunk list, pruned and accounted to the ledger.
    Shared by the serial and morsel-parallel paths, so both skip the
    same segments and the pruning counters mean the same thing.

    Two pruning stages, cheapest first: zone maps (min/max in the
    encoded space, plus zstr collation-order bounds), then the v2
    per-segment skip indexes (inline id list / bloom filter) for
    equality and IN over dictionary columns. Every skipped segment is
    a LazyChunk that never decodes a byte."""
    # prune decisions become trace spans: WHY a query was fast (segments
    # skipped, and by which index) is part of its trace, not just a
    # counter in the scan ledger
    prune_sp = qtrace.span(f"prune {table.name}")
    units = table.scan_units()
    cons = idcons = strcons = ()
    if query.where is not None:
        cons = _zone_constraints(table, query.where)
        idcons, strcons = _index_constraints(table, query.where)
    chunks = []
    zoned = pruned = bchecked = bpruned = 0
    for ch, zones, seg in units:
        if zones is not None:
            zoned += 1
        if cons and _zone_pruned(zones, cons):
            if zones is not None:
                pruned += 1
            continue
        if seg is not None and (idcons or strcons):
            if strcons and _str_pruned(seg, strcons):
                pruned += 1
                continue
            hit = True
            checked = False
            for col, ids in idcons:
                if not seg.has_index(col):
                    continue
                checked = True
                if not seg.maybe_contains(col, ids):
                    hit = False
                    break
            if checked:
                bchecked += 1
            if not hit:
                bpruned += 1
                continue
        chunks.append(ch)
    _note_scan(zoned, pruned, bchecked, bpruned)
    prune_sp.annotate(candidates=zoned, zone_pruned=pruned,
                      bloom_checked=bchecked, bloom_pruned=bpruned,
                      scanned=len(chunks))
    prune_sp.finish()
    return chunks


def _materialize(table: ColumnarTable, query: S.Select,
                 extra_cols: set[str] | None = None) -> tuple[_Env, int]:
    """WHERE-filter the (zone-pruned) chunks and materialize every
    referenced column into one _Env."""
    needed = _needed_cols(table, query, extra_cols)

    # filter per chunk, then materialize needed columns
    chunks = _scan_plan(table, query)
    chunk_sizes = [_chunk_rows(ch) for ch in chunks]
    if query.where is not None:
        prims = _filter_prims(table, query.where)
        if prims is not None:
            # index-list path: survivors come back as ascending
            # positions; chunks with zero survivors never decode the
            # remaining needed columns at all
            idxs = [_select_rows(ch.__getitem__, sz, prims)
                    for ch, sz in zip(chunks, chunk_sizes)]
            n_rows = int(sum(len(i) for i in idxs))
            cols = {}
            for name in needed:
                parts = [ch[name][i] for ch, i in zip(chunks, idxs)
                         if len(i)]
                cols[name] = (np.concatenate(parts) if parts else
                              np.empty(0,
                                       dtype=table.columns[name].np_dtype))
            return _Env(table, cols), n_rows
        masks = []
        for ch, sz in zip(chunks, chunk_sizes):
            env = _Env(table, ch)
            m = env.eval(query.where).arr
            if m.ndim == 0:  # WHERE with no column refs: scalar condition
                m = np.full(sz, bool(m))
            masks.append(m.astype(bool))
        n_rows = int(sum(m.sum() for m in masks))
        cols = {}
        for name in needed:
            parts = [ch[name][m] for ch, m in zip(chunks, masks)]
            cols[name] = (np.concatenate(parts) if parts else
                          np.empty(0, dtype=table.columns[name].np_dtype))
    else:
        n_rows = int(sum(chunk_sizes))
        cols = {}
        for name in needed:
            parts = [ch[name] for ch in chunks]
            cols[name] = (np.concatenate(parts) if parts else
                          np.empty(0, dtype=table.columns[name].np_dtype))
    return _Env(table, cols), n_rows


# -- grouping kernels -------------------------------------------------------

def _sort_ranks(a: np.ndarray) -> np.ndarray:
    """int64 view of one key column that sorts identically to its values.
    Ints pass through; floats/objects are rank-encoded via np.unique
    (ranks are monotone in the values, so lexicographic order over ranks
    == lexicographic order over values — the same invariant the legacy
    radix composition relied on)."""
    if a.dtype.kind == "b":
        return a.astype(np.int64)
    if a.dtype.kind in "iu":
        if a.dtype == np.uint64 and len(a) and int(a.max()) > 2**63 - 1:
            _, inv = np.unique(a, return_inverse=True)
            return inv.astype(np.int64)
        return a.astype(np.int64)
    _, inv = np.unique(a, return_inverse=True)
    return inv.astype(np.int64)


def _group_rows(arrs: list[np.ndarray], first_occurrence: bool):
    """Group rows by the composite key over `arrs`.

    -> (order, bounds_full, n_groups): order is a row permutation with
    groups contiguous and original row order within each group;
    bounds_full has n_groups+1 entries. Group order is first-occurrence
    when `first_occurrence` else ascending-lexicographic over the keys.

    Dispatches between the native O(n) hash-group kernel and numpy
    lexsort via the learned cost model; both are reshaped to the
    requested group order so callers see one deterministic layout.
    """
    ranks = [_sort_ranks(np.ascontiguousarray(a)) for a in arrs]
    n = len(ranks[0]) if ranks else 0
    if n == 0:
        return (np.empty(0, dtype=np.int64), np.zeros(1, dtype=np.int64), 0)
    kernel = _COST.choose(n) if native.load() is not None else "numpy"
    if kernel == "native":
        t0 = time.perf_counter_ns()
        res = native.qx_group(ranks)
        if res is not None:
            order, bounds_full, ng = res
            if not first_occurrence:
                # reorder first-occurrence groups to ascending key order:
                # lexsort the (one-per-group) representatives, then gather
                starts = bounds_full[:-1]
                reps = order[starts]
                perm = np.lexsort([r[reps] for r in ranks][::-1])
                order, bounds_full = _apply_group_perm(
                    order, bounds_full, perm)
            _COST.observe("native", n, time.perf_counter_ns() - t0)
            return order, bounds_full, ng
    t0 = time.perf_counter_ns()
    order = np.lexsort(ranks[::-1]).astype(np.int64)
    changed = np.zeros(n, dtype=bool)
    changed[0] = True
    for r in ranks:
        sr = r[order]
        changed[1:] |= sr[1:] != sr[:-1]
    starts = np.flatnonzero(changed)
    bounds_full = np.append(starts, n).astype(np.int64)
    ng = len(starts)
    if first_occurrence:
        # stable argsort over each group's earliest row restores
        # first-occurrence discovery order
        perm = np.argsort(order[starts], kind="stable")
        order, bounds_full = _apply_group_perm(order, bounds_full, perm)
    _COST.observe("numpy", n, time.perf_counter_ns() - t0)
    return order, bounds_full, ng


def _apply_group_perm(order: np.ndarray, bounds_full: np.ndarray,
                      perm: np.ndarray):
    """Permute whole groups of `order` by `perm` without a Python loop:
    gather each group's segment to its new contiguous position."""
    starts = bounds_full[:-1]
    lengths = (bounds_full[1:] - starts)[perm]
    new_bounds = np.concatenate(
        ([0], np.cumsum(lengths))).astype(np.int64)
    offsets = starts[perm] - new_bounds[:-1]
    idx = np.repeat(offsets, lengths) + np.arange(len(order))
    return order[idx], new_bounds


def _group_order(env: _Env, query: S.Select,
                 n_rows: int) -> tuple[np.ndarray, np.ndarray]:
    """-> (order, bounds) group permutation for the aggregate path.
    Groups come out in ascending key order (the legacy radix-composition
    contract, so encoded and decoded paths emit identical row order)."""
    if query.group_by:
        key_vals = [env.eval(g) for g in query.group_by]
        if n_rows == 0:
            return (np.empty(0, dtype=np.int64),
                    np.empty(0, dtype=np.int64))
        arrs = []
        for kv in key_vals:
            a = kv.arr
            if a.ndim == 0:  # GROUP BY a literal: one group
                a = np.broadcast_to(a, (n_rows,))
            arrs.append(a)
        order, bounds_full, _ = _group_rows(arrs, first_occurrence=False)
        return order, bounds_full[:-1]
    # one group over all rows; zero rows -> zero groups
    return (np.arange(n_rows),
            np.zeros(1 if n_rows else 0, dtype=np.int64))


def _is_agg_query(query: S.Select) -> bool:
    return bool(query.group_by) or query.having is not None or any(
        S.contains_agg(i.expr) for i in query.items)


# -- columnar ORDER BY / LIMIT ----------------------------------------------

def _slice_val(v: _Val, idx) -> _Val:
    w = _Val(v.arr[idx], v.kind, labels=v.labels, unit=v.unit)
    w.dict_ = v.dict_
    return w


def _sort_key(v: _Val) -> np.ndarray:
    """Sortable int64/float64 column matching Python-row-sort semantics.
    Dictionary ids are NOT collation-ordered, so string columns sort by a
    rank table built once over the (small) dictionary, never the rows."""
    a = v.arr
    if v.kind == "str" and v.dict_ is not None:
        n_d = v.dict_.sync_state()[1]
        strs = np.asarray(
            v.dict_.decode_many(np.arange(n_d, dtype=np.uint32)),
            dtype=object)
        rank = np.empty(n_d, dtype=np.int64)
        rank[np.argsort(strs, kind="stable")] = np.arange(
            n_d, dtype=np.int64)
        return rank.take(a.astype(np.int64), mode="clip")
    if v.kind == "enum":
        if not v.labels:
            return a.astype(np.int64)
        labs = np.asarray(v.labels, dtype=object)
        rank = np.empty(len(labs), dtype=np.int64)
        rank[np.argsort(labs, kind="stable")] = np.arange(
            len(labs), dtype=np.int64)
        return rank.take(a.astype(np.int64), mode="clip")
    if v.kind == "obj":
        # _case_select guarantees all-string object arrays
        _, inv = np.unique(a, return_inverse=True)
        return inv.astype(np.int64)
    if a.dtype.kind == "f":
        return a.astype(np.float64)
    return a.astype(np.int64)


def _order_limit_idx(query: S.Select, names: list[str],
                     outs: list[_Val]) -> np.ndarray | None:
    """Encoded ORDER BY + LIMIT: index array selecting/ordering the final
    rows, or None for 'keep everything as is'. Mirrors _order_limit's
    name resolution and reversed-stable-sort semantics via one lexsort."""
    n = max((len(v.arr) for v in outs), default=0)
    if not query.order_by:
        if query.limit is not None and query.limit < n:
            return np.arange(query.limit)
        return None
    keys = []
    for e, desc in query.order_by:
        key_name = S.expr_name(e)
        if key_name in names:
            idx = names.index(key_name)
        elif isinstance(e, S.Col) and e.name in names:
            idx = names.index(e.name)
        else:
            raise QueryError(f"ORDER BY {key_name!r} must appear in SELECT")
        k = _sort_key(outs[idx])
        if desc:
            k = -k
        keys.append(k)
    order = np.lexsort(keys[::-1])
    if query.limit is not None:
        order = order[:query.limit]
    return order


def _finish_columnar(query: S.Select, names: list[str],
                     outs: list[_Val]) -> QueryResult:
    """Sort/limit on encoded columns, decode only the surviving rows."""
    idx = _order_limit_idx(query, names, outs)
    if idx is not None:
        outs = [_slice_val(v, idx) for v in outs]
    decoded = [v.decoded() for v in outs]
    n_out = max((len(d) for d in decoded), default=0)
    rows = [list(r) for r in zip(*decoded)] if n_out else []
    return QueryResult(columns=names, values=rows)


# -- morsel-parallel scan ----------------------------------------------------

def _int_exact(table: ColumnarTable, e) -> bool:
    """True when the expression is guaranteed integer-valued, so
    re-adding per-morsel float64 partial sums is bit-exact regardless
    of the split (the argument PR 7 made for federated SUM/AVG)."""
    if isinstance(e, S.Lit):
        return isinstance(e.value, (bool, int))
    if isinstance(e, S.Col):
        spec = table.columns.get(e.name)
        return spec is not None and spec.kind[0] in "iu"
    if isinstance(e, S.Func) and e.name == "TIME":
        return True
    if isinstance(e, S.BinOp) and e.op in ("+", "-", "*"):
        return (not isinstance(e.right, tuple)
                and _int_exact(table, e.left)
                and _int_exact(table, e.right))
    return False


def _parallel_sites_ok(table: ColumnarTable, query: S.Select,
                       sites: list) -> bool:
    """Aggregates whose morsel split is provably byte-identical to the
    serial scan. LAST is out (cross-morsel timestamp ties), PERCENTILE
    is out (the combine folds sketches, the serial path np.percentile),
    SUM/AVG over float-valued expressions are out (re-association)."""
    for s in sites:
        if s.name == "COUNT" and s.distinct:
            if len(s.args) != 1 or not isinstance(s.args[0], S.Col):
                return False
            spec = table.columns.get(s.args[0].name)
            if spec is None or spec.kind != "str":
                return False  # only dict-id sets union encoded-exactly
            continue
        if s.name in ("COUNT", "MIN", "MAX"):
            continue
        if s.name in ("SUM", "AVG"):
            if (s.args and not isinstance(s.args[0], S.Star)
                    and not _int_exact(table, s.args[0])):
                return False
            continue
        return False
    return True


def _plan_parallel(table: ColumnarTable, query: S.Select):
    """-> (kernel, sites, est_rows) when the morsel path applies to this
    query, else None. DF_QUERY_PARALLEL=1/0 forces the choice; otherwise
    the learned degree model decides, behind a hard floor so queries
    smaller than two morsels never pay pool dispatch."""
    if qpool.in_worker():
        return None
    force = os.environ.get("DF_QUERY_PARALLEL", "").strip()
    if force == "0" or qpool.configured_threads() <= 1:
        return None
    if not _is_agg_query(query):
        return None
    try:
        sites = _agg_sites(query)
    except QueryError:
        return None
    if not _parallel_sites_ok(table, query, sites):
        return None
    est = len(table)
    if force != "1":
        if est < 2 * _morsel_rows():
            return None
        kernel = _DEGREE.choose(est)
    else:
        kernel = "parallel"
    return kernel, sites, est


def _execute_parallel(table: ColumnarTable, query: S.Select,
                      sites: list) -> QueryResult | None:
    """Morsel-parallel aggregate scan. Fixed-row morsels over the
    zone-pruned chunk list fan out on the shared pool; each worker
    filters, groups and reduces its slice into an encoded partial
    (the GIL-released native kernels run concurrently), and the
    partials fold through the cache's exact combine machinery.

    Byte-identity: morsels preserve row order, so the per-group state
    each one emits starts from the same row order the serial scan sees;
    combine_partials(ascending=True) yields ONE partial whose groups
    are ascending-unique — re-grouping that in merge_partials is a
    fixed point, its per-site folds run over single-element groups
    (identity), and ascending group order is exactly the serial
    executor's _group_order contract. Returns None to fall back when
    the pool is unavailable or a dictionary compacted mid-scan."""
    p = qpool.get_pool()
    if p is None:
        return None
    needed = _needed_cols(table, query)
    chunks = _scan_plan(table, query)
    mrows = _morsel_rows()
    morsels: list[tuple[dict, int, int]] = []
    for ch in chunks:
        sz = _chunk_rows(ch)
        for lo in range(0, sz, mrows):
            morsels.append((ch, lo, min(lo + mrows, sz)))
    dict_names = {id(d): cn for cn, d in table.dicts.items()}
    qtrace.annotate(morsels=len(morsels), degree=p.threads)
    where = query.where
    prims = _filter_prims(table, where) if where is not None else None

    def scan_one(m):
        ch, lo, hi = m
        n = hi - lo
        if prims is not None:
            idx = _select_rows(lambda c: ch[c][lo:hi], n, prims)
            cols = {name: ch[name][lo:hi][idx] for name in needed}
            n = len(idx)
        elif where is not None:
            cols = {name: ch[name][lo:hi] for name in needed}
            mask = _Env(table, cols).eval(where).arr
            if mask.ndim == 0:  # no column refs: scalar condition
                mask = np.full(n, bool(mask))
            mask = mask.astype(bool)
            cols = {k: v[mask] for k, v in cols.items()}
            n = int(mask.sum())
        else:
            cols = {name: ch[name][lo:hi] for name in needed}
        used_m: dict = {}
        part = _partial_from_env(table, query, sites, _Env(table, cols),
                                 n, encoded=True, dict_names=dict_names,
                                 used=used_m)
        return part, used_m

    results = p.map(scan_one, morsels) if morsels else []
    used: dict = {}
    for _part, u in results:
        used.update(u)
    combined = combine_partials(table, query,
                                [part for part, _u in results],
                                ascending=True)
    for key, d in used.items():
        if table.dicts.get(key) is not d:
            return None  # dictionary compacted mid-scan: redo serially
    return merge_partials(table, query, [combined])


def execute(table: ColumnarTable, query: S.Select | str) -> QueryResult:
    if isinstance(query, str):
        query = S.parse(query)
    query = _normalize(table, query)
    with qtrace.span(f"scan {table.name}") as tsp:
        res = _execute_traced(table, query, tsp)
    return res


def _execute_traced(table: ColumnarTable, query: S.Select,
                    tsp) -> QueryResult:
    if os.environ.get("DF_QUERY_ENCODED", "1") == "0":
        tsp.annotate(mode="decoded")
        return _execute_decoded(table, query)
    plan = _plan_parallel(table, query)
    t0 = time.perf_counter_ns() if plan is not None else 0
    if plan is not None and plan[0] == "parallel":
        try:
            res = _execute_parallel(table, query, plan[1])
        except _FastUnsupported:
            res = None
        if res is not None:
            _DEGREE.observe("parallel", plan[2],
                            time.perf_counter_ns() - t0)
            tsp.annotate(mode="parallel", est_rows=plan[2])
            return res
        plan = None  # fell back; don't skew the serial coefficient
    tsp.annotate(mode="serial")
    env, n_rows = _materialize(table, query)

    is_agg = _is_agg_query(query)

    names = [i.alias or S.expr_name(i.expr) for i in query.items]
    if not is_agg:
        outs = []
        for i in query.items:
            v = env.eval(i.expr)
            if v.arr.ndim == 0:  # bare literal: broadcast over rows
                v = _Val(np.full(n_rows, v.arr.item()), v.kind)
            outs.append(v)
        return _finish_columnar(query, names, outs)

    order, bounds = _group_order(env, query, n_rows)
    n_groups = len(bounds)
    outs = []
    for i in query.items:
        v = _agg_eval(i.expr, env, order, bounds)
        if v.arr.ndim == 0:  # bare literal: broadcast over groups
            v = _Val(np.full(n_groups, v.arr.item()), v.kind)
        outs.append(v)
    if query.having is not None:
        mask = _agg_eval(query.having, env, order, bounds).arr
        if mask.ndim == 0:
            mask = np.full(n_groups, bool(mask))
        mask = mask.astype(bool)
        outs = [_slice_val(v, mask) for v in outs]
    res = _finish_columnar(query, names, outs)
    if plan is not None:
        _DEGREE.observe("serial", plan[2], time.perf_counter_ns() - t0)
    return res


def _execute_decoded(table: ColumnarTable, query: S.Select) -> QueryResult:
    """Legacy decode-then-Python-sort tail (DF_QUERY_ENCODED=0). Kept as
    the parity reference the encoded path must match byte for byte —
    cli/query_check.py diffs the two on every golden query."""
    env, n_rows = _materialize(table, query)

    is_agg = _is_agg_query(query)

    names = [i.alias or S.expr_name(i.expr) for i in query.items]
    if not is_agg:
        outs = []
        for i in query.items:
            v = env.eval(i.expr)
            if v.arr.ndim == 0:  # bare literal: broadcast over rows
                v = _Val(np.full(n_rows, v.arr.item()), v.kind)
            outs.append(v)
    else:
        order, bounds = _group_order(env, query, n_rows)
        n_groups = len(bounds)
        outs = []
        for i in query.items:
            v = _agg_eval(i.expr, env, order, bounds)
            if v.arr.ndim == 0:  # bare literal: broadcast over groups
                v = _Val(np.full(n_groups, v.arr.item()), v.kind)
            outs.append(v)

    decoded = [v.decoded() for v in outs]
    n_out = max((len(d) for d in decoded), default=0)
    rows = [list(r) for r in zip(*decoded)] if n_out else []

    if query.having is not None:
        mask = _agg_eval(query.having, env, order, bounds).arr
        if mask.ndim == 0:
            mask = np.full(len(rows), bool(mask))
        rows = [r for r, keep in zip(rows, mask.astype(bool)) if keep]

    rows = _order_limit(query, names, rows)
    return QueryResult(columns=names, values=rows)


def _order_limit(query: S.Select, names: list[str],
                 rows: list[list]) -> list[list]:
    """ORDER BY over output columns, then LIMIT (shared by the legacy
    executor and the generic federated merge reduce)."""
    for e, desc in reversed(query.order_by):
        key_name = S.expr_name(e)
        if key_name in names:
            idx = names.index(key_name)
        elif isinstance(e, S.Col) and e.name in names:
            idx = names.index(e.name)
        else:
            raise QueryError(f"ORDER BY {key_name!r} must appear in SELECT")
        rows.sort(key=lambda r: r[idx], reverse=desc)
    if query.limit is not None:
        rows = rows[:query.limit]
    return rows


# -- cluster federation: partial aggregates + merge reduce ------------------
#
# Scatter-gather contract: every shard parses the SAME SQL text and runs
# execute_partial(); the coordinator runs merge_partials() over the shard
# results (its own local partial included). Both sides derive the result
# layout from the same _normalize()d query, so the wire carries no schema.
# Distributive aggregates (SUM/COUNT/MIN/MAX) push down exactly, AVG
# travels as (sum, count), COUNT(DISTINCT) as per-group distinct values,
# LAST as (value, time) pairs resolved by max time, PERCENTILE as a
# mergeable histogram sketch (the one documented-approximate merge).
#
# Column encoding on the wire is version-negotiated per column, not per
# protocol: execute_partial(encoded=True) ships dictionary/enum columns
# as INT id arrays plus a {"dicts": {col: [gen, len]}} manifest, and the
# coordinator remaps ids into its local dictionaries via the dict-sync
# deltas (cluster/dictsync.py) before merging. A shard that predates the
# encoded forms ships plain decoded lists; _inflate_partial() lowers any
# mix of old and new forms to decoded strings and the generic merge
# reduces them — old and new shards interoperate in one scatter.

def _agg_sites(query: S.Select) -> list[S.Func]:
    """Unique aggregate call sites (by display name) across SELECT items
    and HAVING, in discovery order."""
    sites: list[S.Func] = []
    seen: set[str] = set()

    def walk(e) -> None:
        if isinstance(e, S.Func):
            if e.name in S.AGG_FUNCS:
                k = S.expr_name(e)
                if k not in seen:
                    seen.add(k)
                    sites.append(e)
                return
            for a in e.args:
                walk(a)
        elif isinstance(e, S.BinOp):
            walk(e.left)
            if not isinstance(e.right, tuple):
                walk(e.right)
        elif isinstance(e, S.Not):
            walk(e.expr)
        elif isinstance(e, S.Case):
            for c, v in e.whens:
                walk(c)
                walk(v)
            if e.default is not None:
                walk(e.default)

    for item in query.items:
        walk(item.expr)
    if query.having is not None:
        walk(query.having)
    return sites


def _decode_slice(v: _Val, arr: np.ndarray) -> list:
    w = _Val(arr, v.kind, labels=v.labels)
    w.dict_ = v.dict_
    return w.decoded()


def _partial_state(site: S.Func, env: _Env, order: np.ndarray,
                   starts: np.ndarray, ends: np.ndarray) -> list:
    """Per-group mergeable state for one aggregate site (JSON-able,
    decoded — the cross-version compat form)."""
    n_groups = len(starts)
    if n_groups == 0:
        return []
    name = site.name
    if name == "COUNT" and site.distinct:
        if len(site.args) != 1 or isinstance(site.args[0], S.Star):
            raise QueryError("COUNT(DISTINCT) takes exactly one column")
        v = env.eval(site.args[0])
        a = v.arr[order]
        return [_decode_slice(v, np.unique(a[s0:e0]))
                for s0, e0 in zip(starts, ends)]
    if site.distinct:
        raise QueryError(
            f"DISTINCT is only supported in Count(), not {name}")
    if name == "COUNT" or not site.args or isinstance(site.args[0], S.Star):
        return (ends - starts).astype(np.float64).tolist()
    v = env.eval(site.args[0])
    if name == "LAST":
        idx = order[ends - 1]
        vals = _decode_slice(v, v.arr[idx])
        # pair with the row's timestamp so the merge picks the globally
        # newest candidate; without a time column the pick is arbitrary
        t = (env.cols["time"][idx].astype(np.int64).tolist()
             if "time" in env.cols else [0] * n_groups)
        return [[val, int(tt)] for val, tt in zip(vals, t)]
    if v.kind in ("str", "enum", "obj"):
        raise QueryError(
            f"{name} over string column {S.expr_name(site.args[0])!r}")
    a = v.arr.astype(np.float64)[order]
    if name == "SUM":
        return np.add.reduceat(a, starts).tolist()
    if name == "AVG":
        s = np.add.reduceat(a, starts)
        return [[float(x), int(c)] for x, c in zip(s, ends - starts)]
    if name == "MIN":
        return np.minimum.reduceat(a, starts).tolist()
    if name == "MAX":
        return np.maximum.reduceat(a, starts).tolist()
    if name == "PERCENTILE":
        from deepflow_tpu.cluster.sketch import HistogramSketch
        out = []
        for s0, e0 in zip(starts, ends):
            sk = HistogramSketch()
            sk.add_many(a[s0:e0])
            out.append(sk.to_dict())
        return out
    raise QueryError(f"unknown aggregate {name}")


def _partial_state_enc(site: S.Func, env: _Env, order: np.ndarray,
                       starts: np.ndarray, ends: np.ndarray,
                       dict_names: dict, used: dict):
    """Encoded per-site state: float64 arrays for the distributive
    aggregates, dictionary-id sets for COUNT(DISTINCT str). LAST and
    PERCENTILE keep their decoded forms (value+timestamp pairs and
    sketches merge on decoded/abstract state anyway)."""
    n_groups = len(starts)
    if n_groups == 0:
        return []
    name = site.name
    if name == "COUNT" and site.distinct:
        if len(site.args) != 1 or isinstance(site.args[0], S.Star):
            raise QueryError("COUNT(DISTINCT) takes exactly one column")
        v = env.eval(site.args[0])
        key = dict_names.get(id(v.dict_)) if v.kind == "str" else None
        if key is not None:
            used[key] = v.dict_
            a = v.arr[order]
            return {"ed": key,
                    "sets": [np.unique(a[s0:e0]).astype(np.int64).tolist()
                             for s0, e0 in zip(starts, ends)]}
        return _partial_state(site, env, order, starts, ends)
    if site.distinct:
        raise QueryError(
            f"DISTINCT is only supported in Count(), not {name}")
    if name == "COUNT" or not site.args or isinstance(site.args[0], S.Star):
        return {"a": (ends - starts).astype(np.float64)}
    if name in ("LAST", "PERCENTILE"):
        return _partial_state(site, env, order, starts, ends)
    v = env.eval(site.args[0])
    if v.kind in ("str", "enum", "obj"):
        raise QueryError(
            f"{name} over string column {S.expr_name(site.args[0])!r}")
    af = v.arr.astype(np.float64)
    bounds_full = np.append(starts, len(order))
    if name == "SUM":
        return {"a": _group_reduce("SUM", af, order, bounds_full)}
    if name == "AVG":
        return {"avg": [_group_reduce("SUM", af, order, bounds_full),
                        (ends - starts).astype(np.float64)]}
    if name == "MIN":
        return {"a": _group_reduce("MIN", af, order, bounds_full)}
    if name == "MAX":
        return {"a": _group_reduce("MAX", af, order, bounds_full)}
    raise QueryError(f"unknown aggregate {name}")


def _enc_col(v: _Val, arr: np.ndarray, dict_names: dict, used: dict):
    """Self-describing encoded column form for a group-key/item slice, or
    None when only the decoded list form can represent it ('obj')."""
    if v.kind == "str" and v.dict_ is not None:
        key = dict_names.get(id(v.dict_))
        if key is not None:
            used[key] = v.dict_
            return {"e": key,
                    "ids": np.ascontiguousarray(arr, dtype=np.uint32)}
        return None
    if v.kind == "enum":
        return {"n": arr.astype(np.int64), "labels": list(v.labels)}
    if v.kind == "bool":
        return {"a": arr.astype(np.uint8), "k": "bool"}
    if v.kind == "num":
        return {"a": np.ascontiguousarray(arr)}
    return None


def _partial_from_env(table: ColumnarTable, query: S.Select, sites: list,
                      env: _Env, n_rows: int, *, encoded: bool,
                      dict_names: dict, used: dict) -> dict:
    """Group one materialized scope (a whole table scan or a single
    morsel) and build its per-group partial states. The dicts manifest
    is NOT attached here — the caller reads gen/len once after every
    scope it built is done (see execute_partial)."""
    order, bounds = _group_order(env, query, n_rows)
    starts = bounds
    ends = np.append(bounds[1:], len(order))
    n_groups = len(bounds)
    keys = []
    for g in query.group_by:
        v = env.eval(g)
        arr = v.arr[order][bounds] if n_groups else v.arr[:0]
        col = _enc_col(v, arr, dict_names, used) if encoded else None
        keys.append(col if col is not None else _decode_slice(v, arr))
    items: dict[str, object] = {}
    for idx, item in enumerate(query.items):
        if S.contains_agg(item.expr):
            continue
        v = env.eval(item.expr)
        if v.arr.ndim == 0:   # bare literal: broadcast over groups
            if encoded and v.kind == "num":
                items[str(idx)] = {"a": np.full(n_groups, v.arr.item())}
            else:
                items[str(idx)] = [v.arr.item()] * n_groups
            continue
        arr = v.arr[order][bounds] if n_groups else v.arr[:0]
        col = _enc_col(v, arr, dict_names, used) if encoded else None
        items[str(idx)] = col if col is not None else _decode_slice(v, arr)
    if encoded:
        site_states = {S.expr_name(s): _partial_state_enc(
            s, env, order, starts, ends, dict_names, used) for s in sites}
    else:
        site_states = {S.expr_name(s): _partial_state(s, env, order,
                                                      starts, ends)
                       for s in sites}
    return {"kind": "agg", "n_groups": n_groups, "keys": keys,
            "items": items, "sites": site_states}


def execute_partial(table: ColumnarTable, query: S.Select | str, *,
                    encoded: bool = False) -> dict:
    """Shard-local half of a federated query. Row queries run fully
    (ORDER/LIMIT pushed down — a shard-local top-k is a superset of the
    global top-k's contribution); aggregate queries return per-group
    partial states.

    encoded=False keys groups by DECODED values (the cross-version wire
    form every coordinator understands). encoded=True ships dictionary
    ids + a {"dicts": {col: [gen, len]}} manifest instead; the caller is
    responsible for remapping ids into its own dictionaries (dictsync)
    before merging."""
    if isinstance(query, str):
        query = S.parse(query)
    if not _is_agg_query(_normalize(table, query)):
        res = execute(table, query)
        return {"kind": "rows", "columns": res.columns,
                "values": res.values}
    query = _normalize(table, query)
    if encoded and os.environ.get("DF_QUERY_ENCODED", "1") == "0":
        encoded = False
    sites = _agg_sites(query)
    needs_time = (any(s.name == "LAST" for s in sites)
                  and "time" in table.columns)
    with qtrace.span(f"scan.partial {table.name}", encoded=encoded) as sp:
        return _execute_partial_traced(table, query, sites, needs_time,
                                       encoded, sp)


def _execute_partial_traced(table: ColumnarTable, query: S.Select,
                            sites, needs_time: bool, encoded: bool,
                            sp) -> dict:
    env, n_rows = _materialize(
        table, query, extra_cols={"time"} if needs_time else None)
    sp.annotate(rows=n_rows)
    dict_names = ({id(d): cn for cn, d in table.dicts.items()}
                  if encoded else {})
    used: dict = {}  # dict-columns actually shipped as ids
    out = _partial_from_env(table, query, sites, env, n_rows,
                            encoded=encoded, dict_names=dict_names,
                            used=used)
    if used:
        # The gen/len manifest is read AFTER building: the dictionary only
        # grows in place, so len covers every id shipped above. If
        # compaction swapped the dictionary object out mid-build, the ids
        # we encoded belong to the retired object — recompute decoded.
        dicts = {}
        for key, d in used.items():
            if table.dicts.get(key) is not d:
                return execute_partial(table, query, encoded=False)
            g, ln, _ver = d.sync_state()
            dicts[key] = [g, ln]
        out["dicts"] = dicts
    return out


def _merge_site(site: S.Func, states: list) -> object:
    """Combine one aggregate site's per-shard states into the final
    scalar for one group."""
    name = site.name
    if name == "COUNT" and site.distinct:
        u: set = set()
        for s in states:
            u.update(s)
        return float(len(u))
    if name in ("COUNT", "SUM"):
        return float(sum(states))
    if name == "MIN":
        return float(min(states))
    if name == "MAX":
        return float(max(states))
    if name == "AVG":
        tot = sum(s for s, _ in states)
        n = sum(c for _, c in states)
        return float(tot) / max(n, 1)
    if name == "LAST":
        return max(states, key=lambda vt: vt[1])[0]
    if name == "PERCENTILE":
        from deepflow_tpu.cluster.sketch import HistogramSketch
        merged = HistogramSketch()
        for d in states:
            merged.merge(HistogramSketch.from_dict(d))
        p_arg = site.args[1] if len(site.args) == 2 else None
        if not isinstance(p_arg, S.Lit):
            raise QueryError(
                "Percentile(col, p) needs a literal p to federate")
        return merged.percentile(float(p_arg.value))
    raise QueryError(f"unknown aggregate {name}")


_CMP = {"=": lambda l, r: l == r, "!=": lambda l, r: l != r,
        "<": lambda l, r: l < r, "<=": lambda l, r: l <= r,
        ">": lambda l, r: l > r, ">=": lambda l, r: l >= r}


def _scalar_eval(e, agg_vals: dict, named: dict):
    """Evaluate one merged group's output expression: aggregate sites
    resolve to their merged values, everything else must be a group key
    (or shipped non-agg item) looked up by display name."""
    if isinstance(e, S.Lit):
        return e.value
    if isinstance(e, S.Func) and e.name in S.AGG_FUNCS:
        return agg_vals[S.expr_name(e)]
    if not S.contains_agg(e):
        key = S.expr_name(e)
        if key in named:
            return named[key]
        if isinstance(e, (S.Col, S.Func)):
            raise QueryError(
                f"federated merge cannot evaluate {key!r}: "
                "not a group key or aggregate")
    if isinstance(e, S.Not):
        return not _scalar_eval(e.expr, agg_vals, named)
    if isinstance(e, S.Case):
        for c, v in e.whens:
            if _scalar_eval(c, agg_vals, named):
                return _scalar_eval(v, agg_vals, named)
        return (_scalar_eval(e.default, agg_vals, named)
                if e.default is not None else None)
    if isinstance(e, S.BinOp):
        op = e.op
        if op == "AND":
            return bool(_scalar_eval(e.left, agg_vals, named)) and \
                bool(_scalar_eval(e.right, agg_vals, named))
        if op == "OR":
            return bool(_scalar_eval(e.left, agg_vals, named)) or \
                bool(_scalar_eval(e.right, agg_vals, named))
        if op == "IN":
            lv = _scalar_eval(e.left, agg_vals, named)
            return lv in tuple(lit.value for lit in e.right)
        if op == "LIKE":
            lv = _scalar_eval(e.left, agg_vals, named)
            return _like_to_pred(e.right.value)(str(lv))
        left = _scalar_eval(e.left, agg_vals, named)
        right = _scalar_eval(e.right, agg_vals, named)
        if op in _CMP:
            return _CMP[op](left, right)
        lf, rf = float(left), float(right)
        if op == "+":
            return lf + rf
        if op == "-":
            return lf - rf
        if op == "*":
            return lf * rf
        if op == "/":
            return lf / rf if rf else 0.0
        raise QueryError(f"unknown op {op}")
    raise QueryError(f"cannot merge-evaluate {e!r}")


# -- encoded merge: vectorized fast path with decoded fallback --------------

class _FastUnsupported(Exception):
    """Internal: the vectorized merge/combine can't represent this query
    or partial form exactly — fall back to the decoded generic path."""


def _table_dict(table: ColumnarTable, key: str):
    d = table.dicts.get(key)
    if d is None:
        raise QueryError(f"unknown dictionary column {key!r} in partial")
    return d


def _col_form(c, size: int):
    """-> (values_arr, int64_key_arr, meta) for an encoded partial column;
    raises _FastUnsupported for decoded lists / float keys / unknown
    forms (those take the generic merge)."""
    if isinstance(c, dict):
        if "e" in c:
            a = np.asarray(c["ids"])
            if len(a) != size:
                raise _FastUnsupported
            return a, a.astype(np.int64), ("e", c["e"])
        if "n" in c:
            a = np.asarray(c["n"])
            if len(a) != size:
                raise _FastUnsupported
            return a, a.astype(np.int64), ("n", tuple(c["labels"]))
        if "a" in c:
            a = np.asarray(c["a"])
            if len(a) != size or a.dtype.kind not in "iub":
                raise _FastUnsupported
            return a, a.astype(np.int64), ("a", c.get("k", "num"))
    raise _FastUnsupported


def _form_val(cat: np.ndarray, meta: tuple, sel, decoder) -> _Val:
    """Rebuild a _Val from a concatenated encoded column at `sel`."""
    kindm, info = meta
    a = cat[sel]
    if kindm == "e":
        v = _Val(a.astype(np.uint32), "str")
        v.dict_ = decoder(info)
        return v
    if kindm == "n":
        return _Val(a, "enum", labels=tuple(info))
    if info == "bool":
        return _Val(a, "bool")
    return _Val(a)


def _as_bool(a: np.ndarray, n: int) -> np.ndarray:
    a = np.asarray(a)
    if a.ndim == 0:
        return np.full(n, bool(a))
    return a.astype(bool)


def _vec_eval(e, aggs: dict, named: dict, n: int) -> _Val:
    """Vectorized mirror of _scalar_eval over merged group columns.
    Raises _FastUnsupported wherever array semantics could diverge from
    the scalar path (CASE without vectorizable shape, cross-dictionary
    string compares, LIKE over non-strings) so exactness is preserved by
    falling back rather than approximating."""
    if isinstance(e, S.Lit):
        if isinstance(e.value, str):
            raise _FastUnsupported
        return _Val(np.asarray(e.value, dtype=np.float64))
    if isinstance(e, S.Func) and e.name in S.AGG_FUNCS:
        k = S.expr_name(e)
        if k not in aggs:
            raise _FastUnsupported
        return _Val(aggs[k])
    if not S.contains_agg(e):
        k = S.expr_name(e)
        if k in named:
            return named[k]
        if isinstance(e, (S.Col, S.Func)):
            raise QueryError(
                f"federated merge cannot evaluate {k!r}: "
                "not a group key or aggregate")
    if isinstance(e, S.Not):
        v = _vec_eval(e.expr, aggs, named, n)
        return _Val(~_as_bool(v.arr, n), "bool")
    if isinstance(e, S.BinOp):
        op = e.op
        if op in ("AND", "OR"):
            l = _as_bool(_vec_eval(e.left, aggs, named, n).arr, n)
            r = _as_bool(_vec_eval(e.right, aggs, named, n).arr, n)
            return _Val(l & r if op == "AND" else l | r, "bool")
        if op == "IN":
            lv = _vec_eval(e.left, aggs, named, n)
            vals = tuple(lit.value for lit in e.right)
            if lv.kind == "str":
                ids = [lv.dict_.lookup(s) for s in vals
                       if isinstance(s, str)]
                ids = np.asarray([i for i in ids if i is not None],
                                 dtype=np.uint32)
                return _Val(_isin(lv.arr, ids), "bool")
            if lv.kind == "enum":
                ids = [i for i, s in enumerate(lv.labels) if s in vals]
                return _Val(np.isin(lv.arr, ids), "bool")
            if lv.kind == "obj":
                raise _FastUnsupported
            return _Val(np.isin(lv.arr, vals), "bool")
        if op == "LIKE":
            lv = _vec_eval(e.left, aggs, named, n)
            pred = _like_to_pred(e.right.value)
            if lv.kind == "str":
                return _Val(_isin(lv.arr, lv.dict_.match_ids(pred)),
                            "bool")
            if lv.kind == "enum":
                ids = [i for i, s in enumerate(lv.labels) if pred(s)]
                return _Val(np.isin(lv.arr, ids), "bool")
            raise _FastUnsupported
        if op in _CMP:
            rv_raw = e.right
            if isinstance(rv_raw, S.Lit) and isinstance(rv_raw.value, str):
                if op not in ("=", "!="):
                    raise _FastUnsupported
                lv = _vec_eval(e.left, aggs, named, n)
                if lv.kind == "str":
                    sid = lv.dict_.lookup(rv_raw.value)
                    code = (np.uint32(sid) if sid is not None
                            else np.uint32(0xFFFFFFFF))
                elif lv.kind == "enum":
                    try:
                        code = lv.labels.index(rv_raw.value)
                    except ValueError:
                        code = -1
                else:
                    raise _FastUnsupported
                res = lv.arr == code if op == "=" else lv.arr != code
                return _Val(np.asarray(res), "bool")
            lv = _vec_eval(e.left, aggs, named, n)
            rv = _vec_eval(rv_raw, aggs, named, n)
            if (lv.kind in ("str", "enum", "obj")
                    or rv.kind in ("str", "enum", "obj")):
                if (lv.kind == "str" and rv.kind == "str"
                        and lv.dict_ is rv.dict_ and op in ("=", "!=")):
                    res = (lv.arr == rv.arr if op == "="
                           else lv.arr != rv.arr)
                    return _Val(res, "bool")
                raise _FastUnsupported
            return _Val(np.asarray(_CMP[op](lv.arr, rv.arr)), "bool")
        lv = _vec_eval(e.left, aggs, named, n)
        rv = _vec_eval(e.right, aggs, named, n)
        if lv.kind not in ("num", "bool") or rv.kind not in ("num", "bool"):
            raise _FastUnsupported
        l = lv.arr.astype(np.float64)
        r = rv.arr.astype(np.float64)
        if op == "+":
            return _Val(l + r)
        if op == "-":
            return _Val(l - r)
        if op == "*":
            return _Val(l * r)
        if op == "/":
            with np.errstate(divide="ignore", invalid="ignore"):
                return _Val(np.where(r != 0, l / np.where(r == 0, 1, r),
                                     0.0))
        raise _FastUnsupported
    raise _FastUnsupported


def _merge_fast(table: ColumnarTable, query: S.Select, names: list[str],
                sites: list, site_keys: list[str], parts: list[dict],
                decoder) -> QueryResult:
    """Vectorized merge over fully-encoded partials: concatenate group-key
    int columns, one hash-group pass, reduceat the site arrays. Any form
    it can't fold exactly raises _FastUnsupported (caller falls back)."""
    live = [p for p in parts if int(p.get("n_groups", 0)) > 0]
    if not live:
        return QueryResult(columns=names, values=[])
    sizes = [int(p["n_groups"]) for p in live]
    K = len(query.group_by)
    # group-key columns, concatenated across partials
    key_vals: list[tuple[np.ndarray, tuple]] = []
    key_ints: list[np.ndarray] = []
    for ki in range(K):
        vals, ints, metas = [], [], set()
        for p, sz in zip(live, sizes):
            cols = p.get("keys", [])
            if ki >= len(cols):
                raise _FastUnsupported
            a, ia, m = _col_form(cols[ki], sz)
            vals.append(a)
            ints.append(ia)
            metas.add(m)
        if len(metas) != 1:
            raise _FastUnsupported  # mixed forms across shard versions
        key_vals.append((np.concatenate(vals), metas.pop()))
        key_ints.append(np.concatenate(ints))
    # shipped non-aggregate item columns
    item_cols: dict[str, tuple[np.ndarray, tuple]] = {}
    for idx, item in enumerate(query.items):
        if S.contains_agg(item.expr):
            continue
        si = str(idx)
        vals, metas = [], set()
        for p, sz in zip(live, sizes):
            c = p.get("items", {}).get(si)
            if c is None:
                raise _FastUnsupported
            a, _ia, m = _col_form(c, sz)
            vals.append(a)
            metas.add(m)
        if len(metas) != 1:
            raise _FastUnsupported
        item_cols[si] = (np.concatenate(vals), metas.pop())
    # site states: only plain-array and (sum,count) forms vectorize
    site_states: dict[str, tuple[str, list]] = {}
    for s, sk in zip(sites, site_keys):
        form = None
        acc = []
        for p, sz in zip(live, sizes):
            st = p.get("sites", {}).get(sk)
            if not isinstance(st, dict):
                raise _FastUnsupported
            if "a" in st:
                f = "a"
                a = np.asarray(st["a"], dtype=np.float64)
                if len(a) != sz:
                    raise _FastUnsupported
                acc.append((a,))
            elif "avg" in st:
                f = "avg"
                ss = np.asarray(st["avg"][0], dtype=np.float64)
                cc = np.asarray(st["avg"][1], dtype=np.float64)
                if len(ss) != sz or len(cc) != sz:
                    raise _FastUnsupported
                acc.append((ss, cc))
            else:
                raise _FastUnsupported
            if form is None:
                form = f
            elif form != f:
                raise _FastUnsupported
        site_states[sk] = (form, acc)

    total = sum(sizes)
    if K == 0:
        order = np.arange(total, dtype=np.int64)
        bounds_full = np.array([0, total], dtype=np.int64)
        ng = 1
    else:
        # first-occurrence order == the generic merge's discovery order
        order, bounds_full, ng = _group_rows(key_ints,
                                             first_occurrence=True)
    starts = bounds_full[:-1]
    rep = order[starts]

    aggs: dict[str, np.ndarray] = {}
    for s, sk in zip(sites, site_keys):
        form, acc = site_states[sk]
        if form == "a":
            cat = np.concatenate([a for (a,) in acc])[order]
            if s.name in ("COUNT", "SUM"):
                aggs[sk] = np.add.reduceat(cat, starts)
            elif s.name == "MIN":
                aggs[sk] = np.minimum.reduceat(cat, starts)
            elif s.name == "MAX":
                aggs[sk] = np.maximum.reduceat(cat, starts)
            else:
                raise _FastUnsupported
        else:
            if s.name != "AVG":
                raise _FastUnsupported
            ssum = np.concatenate([x for x, _c in acc])[order]
            scnt = np.concatenate([c for _x, c in acc])[order]
            ms = np.add.reduceat(ssum, starts)
            mc = np.add.reduceat(scnt, starts)
            aggs[sk] = ms / np.maximum(mc, 1)

    named: dict[str, _Val] = {}
    for gexpr, (cat, meta) in zip(query.group_by, key_vals):
        named[S.expr_name(gexpr)] = _form_val(cat, meta, rep, decoder)
    item_vals: dict[str, _Val] = {}
    for idx, item in enumerate(query.items):
        si = str(idx)
        if si in item_cols:
            cat, meta = item_cols[si]
            v = _form_val(cat, meta, rep, decoder)
            item_vals[si] = v
            named[S.expr_name(item.expr)] = v
            if item.alias:
                named[item.alias] = v
    n_cur = ng
    if query.having is not None:
        hv = _vec_eval(query.having, aggs, named, n_cur)
        mask = _as_bool(hv.arr, n_cur)
        aggs = {k: v[mask] for k, v in aggs.items()}
        named = {k: _slice_val(v, mask) for k, v in named.items()}
        item_vals = {k: _slice_val(v, mask) for k, v in item_vals.items()}
        n_cur = int(mask.sum())
    outs = []
    for idx, item in enumerate(query.items):
        if not S.contains_agg(item.expr):
            outs.append(item_vals[str(idx)])
            continue
        v = _vec_eval(item.expr, aggs, named, n_cur)
        if v.arr.ndim == 0:
            v = _Val(np.full(n_cur, v.arr.item()), v.kind)
        outs.append(v)
    return _finish_columnar(query, names, outs)


def _col_decoded(c, decoder) -> list:
    """Lower any partial column form to the decoded list form."""
    if isinstance(c, list):
        return c
    if isinstance(c, dict):
        if "e" in c:
            ids = np.asarray(c["ids"], dtype=np.uint32)
            return decoder(c["e"]).decode_many(ids)
        if "n" in c:
            lab = list(c["labels"])
            return [lab[int(i)] for i in np.asarray(c["n"]).tolist()]
        if "a" in c:
            a = np.asarray(c["a"])
            if c.get("k") == "bool":
                return a.astype(bool).tolist()
            return a.tolist()
    raise QueryError("unrecognized partial column form")


def _inflate_partial(p: dict, decoder) -> dict:
    """Lower an encoded partial to the decoded compat form so the generic
    merge can join it against partials from any shard version."""
    if not p or p.get("kind") != "agg":
        return p
    q = dict(p)
    q["keys"] = [_col_decoded(c, decoder) for c in p.get("keys", [])]
    q["items"] = {k: _col_decoded(v, decoder)
                  for k, v in p.get("items", {}).items()}
    sites = {}
    for sk, st in p.get("sites", {}).items():
        if isinstance(st, dict):
            if "a" in st:
                sites[sk] = np.asarray(st["a"], dtype=np.float64).tolist()
            elif "avg" in st:
                s_arr = np.asarray(st["avg"][0], dtype=np.float64)
                c_arr = np.asarray(st["avg"][1], dtype=np.float64)
                sites[sk] = [[float(x), int(c)]
                             for x, c in zip(s_arr.tolist(),
                                             c_arr.tolist())]
            elif "ed" in st:
                d = decoder(st["ed"])
                sites[sk] = [d.decode_many(np.asarray(g, dtype=np.uint32))
                             for g in st["sets"]]
            else:
                raise QueryError("unrecognized partial site form")
        else:
            sites[sk] = st
    q["sites"] = sites
    return q


def merge_partials(table: ColumnarTable, query: S.Select | str,
                   partials: list[dict], *, decoder=None) -> QueryResult:
    """Coordinator reduce step over execute_partial() results (the
    local shard's partial included). HAVING / ORDER BY / LIMIT apply only
    here, at the top.

    Fully-encoded partials (whose ids the caller already remapped into
    the decoder's dictionary space — cluster/dictsync.py) merge on the
    vectorized int-key fast path; anything else, including partials from
    pre-encoding shards, is lowered to decoded values and joins on the
    generic per-group path. decoder maps a dict column name to a
    Dictionary; defaults to this table's own dictionaries."""
    with qtrace.span("merge.partials", partials=len(partials)):
        return _merge_partials_impl(table, query, partials,
                                    decoder=decoder)


def _merge_partials_impl(table: ColumnarTable, query: S.Select | str,
                         partials: list[dict], *,
                         decoder=None) -> QueryResult:
    if isinstance(query, str):
        query = S.parse(query)
    query = _normalize(table, query)
    names = [i.alias or S.expr_name(i.expr) for i in query.items]
    parts = [p for p in partials if p]
    if not _is_agg_query(query):
        rows = []
        for p in parts:
            if p.get("kind") != "rows":
                raise QueryError("shard returned mismatched partial kind")
            rows.extend(list(r) for r in p.get("values", []))
        return QueryResult(columns=names,
                           values=_order_limit(query, names, rows))
    for p in parts:
        if p.get("kind") != "agg":
            raise QueryError("shard returned mismatched partial kind")
    sites = _agg_sites(query)
    site_keys = [S.expr_name(s) for s in sites]
    if decoder is None:
        decoder = lambda key: _table_dict(table, key)  # noqa: E731
    if os.environ.get("DF_QUERY_ENCODED", "1") != "0":
        try:
            return _merge_fast(table, query, names, sites, site_keys,
                               parts, decoder)
        except _FastUnsupported:
            pass
    parts = [_inflate_partial(p, decoder) for p in parts]
    groups: dict[tuple, dict] = {}
    group_seq: list[tuple] = []
    for p in parts:
        keys = p.get("keys", [])
        for gi in range(int(p.get("n_groups", 0))):
            kt = tuple(col[gi] for col in keys)
            g = groups.get(kt)
            if g is None:
                g = groups[kt] = {
                    "items": {k: v[gi]
                              for k, v in p.get("items", {}).items()},
                    "sites": {sk: [] for sk in site_keys}}
                group_seq.append(kt)
            for sk in site_keys:
                g["sites"][sk].append(p["sites"][sk][gi])
    rows = []
    for kt in group_seq:
        g = groups[kt]
        merged = {sk: _merge_site(s, g["sites"][sk])
                  for s, sk in zip(sites, site_keys)}
        named: dict[str, object] = {}
        for gexpr, kv in zip(query.group_by, kt):
            named[S.expr_name(gexpr)] = kv
        for idx, item in enumerate(query.items):
            if not S.contains_agg(item.expr):
                v = g["items"].get(str(idx))
                named[S.expr_name(item.expr)] = v
                if item.alias:
                    named[item.alias] = v
        if query.having is not None and \
                not _scalar_eval(query.having, merged, named):
            continue
        rows.append([
            (g["items"].get(str(idx))
             if not S.contains_agg(item.expr)
             else _scalar_eval(item.expr, merged, named))
            for idx, item in enumerate(query.items)])
    return QueryResult(columns=names,
                       values=_order_limit(query, names, rows))


def combine_partials(table: ColumnarTable, query: S.Select | str,
                     parts: list[dict], *, ascending: bool = False) -> dict:
    """Fold several ENCODED partials over disjoint row sets (per-time-
    bucket cache slices, per-morsel scan results) into ONE partial equal
    to a single scan of their union. Exact for every supported site form
    — including PERCENTILE, whose histogram-sketch merge is bin-exact
    (only the percentile() readout approximates). LAST is excluded:
    cross-bucket timestamp ties could resolve differently than a single
    scan. Raises _FastUnsupported for anything it can't fold exactly.

    ascending=True emits groups in ascending key order instead of
    first-occurrence — the morsel-parallel path needs the combined
    partial to match the serial executor's _group_order layout so the
    final merge is byte-identical."""
    if isinstance(query, str):
        query = S.parse(query)
    query = _normalize(table, query)
    if not _is_agg_query(query):
        raise _FastUnsupported
    sites = _agg_sites(query)
    site_keys = [S.expr_name(s) for s in sites]
    if any(s.name == "LAST" for s in sites):
        raise _FastUnsupported
    for p in parts:
        if not p or p.get("kind") != "agg":
            raise _FastUnsupported
    live = [p for p in parts if int(p.get("n_groups", 0)) > 0]
    K = len(query.group_by)
    item_ids = [str(i) for i, it in enumerate(query.items)
                if not S.contains_agg(it.expr)]
    if not live:
        return {"kind": "agg", "n_groups": 0,
                "keys": [[] for _ in range(K)],
                "items": {si: [] for si in item_ids},
                "sites": {sk: [] for sk in site_keys}}
    sizes = [int(p["n_groups"]) for p in live]
    key_vals, key_ints = [], []
    for ki in range(K):
        vals, ints, metas = [], [], set()
        for p, sz in zip(live, sizes):
            a, ia, m = _col_form(p.get("keys", [])[ki], sz)
            vals.append(a)
            ints.append(ia)
            metas.add(m)
        if len(metas) != 1:
            raise _FastUnsupported
        key_vals.append((np.concatenate(vals), metas.pop()))
        key_ints.append(np.concatenate(ints))
    item_cols = {}
    for si in item_ids:
        vals, metas = [], set()
        for p, sz in zip(live, sizes):
            c = p.get("items", {}).get(si)
            if c is None:
                raise _FastUnsupported
            a, _ia, m = _col_form(c, sz)
            vals.append(a)
            metas.add(m)
        if len(metas) != 1:
            raise _FastUnsupported
        item_cols[si] = (np.concatenate(vals), metas.pop())

    total = sum(sizes)
    if K == 0:
        order = np.arange(total, dtype=np.int64)
        bounds_full = np.array([0, total], dtype=np.int64)
        ng = 1
    else:
        order, bounds_full, ng = _group_rows(
            key_ints, first_occurrence=not ascending)
    starts = bounds_full[:-1]
    ends = bounds_full[1:]
    rep = order[starts]

    def rebuild(cat, meta):
        kindm, info = meta
        a = cat[rep]
        if kindm == "e":
            return {"e": info, "ids": a.astype(np.uint32)}
        if kindm == "n":
            return {"n": a.astype(np.int64), "labels": list(info)}
        if info == "bool":
            return {"a": a, "k": "bool"}
        return {"a": a}

    out_keys = [rebuild(cat, meta) for cat, meta in key_vals]
    out_items = {si: rebuild(cat, meta)
                 for si, (cat, meta) in item_cols.items()}
    out_sites = {}
    for s, sk in zip(sites, site_keys):
        states = [p["sites"].get(sk) for p in live]
        if s.name == "PERCENTILE":
            if not all(isinstance(st, list) for st in states):
                raise _FastUnsupported
            from deepflow_tpu.cluster.sketch import HistogramSketch
            cat = [d for st in states for d in st]
            merged = []
            for s0, e0 in zip(starts.tolist(), ends.tolist()):
                hs = HistogramSketch()
                for m in order[s0:e0].tolist():
                    hs.merge(HistogramSketch.from_dict(cat[m]))
                merged.append(hs.to_dict())
            out_sites[sk] = merged
            continue
        if all(isinstance(st, dict) and "a" in st for st in states):
            cat = np.concatenate(
                [np.asarray(st["a"], dtype=np.float64)
                 for st in states])[order]
            if s.name in ("COUNT", "SUM"):
                out_sites[sk] = {"a": np.add.reduceat(cat, starts)}
            elif s.name == "MIN":
                out_sites[sk] = {"a": np.minimum.reduceat(cat, starts)}
            elif s.name == "MAX":
                out_sites[sk] = {"a": np.maximum.reduceat(cat, starts)}
            else:
                raise _FastUnsupported
            continue
        if all(isinstance(st, dict) and "avg" in st for st in states):
            if s.name != "AVG":
                raise _FastUnsupported
            ssum = np.concatenate(
                [np.asarray(st["avg"][0], dtype=np.float64)
                 for st in states])[order]
            scnt = np.concatenate(
                [np.asarray(st["avg"][1], dtype=np.float64)
                 for st in states])[order]
            out_sites[sk] = {"avg": [np.add.reduceat(ssum, starts),
                                     np.add.reduceat(scnt, starts)]}
            continue
        if all(isinstance(st, dict) and "ed" in st for st in states):
            ed_keys = {st["ed"] for st in states}
            if len(ed_keys) != 1:
                raise _FastUnsupported
            cat = [g for st in states for g in st["sets"]]
            merged = []
            for s0, e0 in zip(starts.tolist(), ends.tolist()):
                u: set = set()
                for m in order[s0:e0].tolist():
                    u.update(int(x) for x in cat[m])
                merged.append(sorted(u))
            out_sites[sk] = {"ed": ed_keys.pop(), "sets": merged}
            continue
        raise _FastUnsupported

    out = {"kind": "agg", "n_groups": int(ng), "keys": out_keys,
           "items": out_items, "sites": out_sites}
    dicts: dict[str, list] = {}
    for p in live:
        for key, (g, ln) in (p.get("dicts") or {}).items():
            cur = dicts.get(key)
            if cur is None:
                dicts[key] = [int(g), int(ln)]
            elif cur[0] != int(g):
                raise _FastUnsupported  # gen flip between slices
            else:
                cur[1] = max(cur[1], int(ln))
    if dicts:
        out["dicts"] = dicts
    return out
