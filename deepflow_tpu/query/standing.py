"""Standing queries: registered DF-SQL maintained incrementally at ingest.

Reference analog: continuously-evaluated dashboard/alert queries
(ROADMAP item 4). A registered query with decomposable aggregates is
never re-executed from scratch on a poll: table append/flush hooks
(store/table.py change listeners) mark the query dirty, the refresher
re-folds exactly the 60s buckets whose write marks moved — through
``QueryCache.standing_fold``, so standing and ad-hoc evaluations of the
same SQL share warm bucket partials AND the cluster-wide distributed
partial cache — slides the window by dropping expired buckets, and
publishes a result delta under a monotone generation to every
subscriber. Cost per update is O(changed buckets), not O(window).

Correctness contract: every emitted result is byte-identical to a
from-scratch ``engine.execute`` of the same windowed SQL at the same
change token. ``DF_STANDING=0`` kills the incremental path (every
refresh executes from scratch) with an identical push surface either
way; ``DF_STANDING_VERIFY=1`` asserts the equivalence on every refresh.

Federation: when cluster peers are alive, refreshes route through
``FederationCoordinator.sql_query`` — the PR 12 if_state/unchanged
machinery means only shards whose change token moved recompute, and the
coordinator's warm fast path turns a no-change tick into zero work.

Self-telemetry: a conserved ``query.standing`` hop ledger —
emitted = updates enqueued to subscribers, delivered = drained by
poll/SSE, dropped{subscriber_lag|closed}, in_flight = still queued.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import deque

from deepflow_tpu.query import engine
from deepflow_tpu.query import sql as S
from deepflow_tpu.query.cache import change_token, normalize_sql

MAX_PENDING = 256       # per-subscriber queue bound (drop-oldest past it)
IDLE_REAP_S = 300.0     # forget subscribers that stopped polling
MIN_GAP_S = 0.5         # per-query refresh debounce (2Hz ceiling): under
                        # an append storm the refold waits the burst out
FED_TICK_S = 0.5        # remote-change poll cadence when federated
# Refresher duty-cycle budget: after a wake that spent T seconds
# folding, nap T * (1/REFRESH_BUDGET - 1) (capped) before folding
# again, bounding standing-query CPU to ~REFRESH_BUDGET of wall time.
# Under an ingest burst freshness degrades (updates coalesce into
# fewer, larger generations) — ingest throughput does not. This is
# what keeps the bench standing-overhead gate under 2% with a
# dashboard's worth of registered queries.
REFRESH_BUDGET = 0.02
MAX_NAP_S = 2.0


def _num(v) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return 0.0


class StandingQuery:
    """One registered query + its maintained state (single-refresher
    mutation; readers go through the registry lock for gen/rows)."""

    def __init__(self, name: str, table, sql: str, select: S.Select,
                 window_s: float, org, verify: bool) -> None:
        self.name = name
        self.table = table
        self.sql = sql
        self.select = select
        self.window_s = float(window_s or 0.0)
        self.org = org
        self.extra_key = None if org is None else ("org", org)
        self.verify = verify
        self.gen = 0
        self.columns: list[str] = []
        self.rows: list[list] = []
        self.token = None
        self.last_refresh = 0.0
        self.last_ms = 0.0
        self.lock = threading.Lock()
        self.counters = {"refreshes": 0, "incremental": 0, "full": 0,
                         "skipped": 0, "unchanged": 0, "errors": 0,
                         "verify_failures": 0, "fed_refreshes": 0,
                         "fed_warm": 0, "fed_shards_unchanged": 0,
                         "fed_shards_refetched": 0, "buckets_folded": 0,
                         "buckets_reused": 0, "buckets_scanned": 0}

    def summary(self) -> dict:
        return {"name": self.name, "table": self.table.name,
                "sql": self.sql, "window_s": self.window_s,
                "org_id": self.org, "gen": self.gen,
                "rows": len(self.rows),
                "last_ms": round(self.last_ms, 3), **self.counters}


class Subscription:
    """One consumer of standing-query updates: a bounded, generation-
    ordered queue. Exactly-once per (subscriber, generation): each
    update enqueues once; poll drains each element once."""

    def __init__(self, sid: str, names: set[str] | None) -> None:
        self.id = sid
        self.names = names  # None = every standing query
        self.pending: deque = deque()
        self.cond = threading.Condition()
        self.closed = False
        self.last_seen = time.monotonic()
        self.delivered = 0

    def wants(self, name: str) -> bool:
        return self.names is None or name in self.names


class StandingQueryRegistry:
    """The registry + refresher: owns every StandingQuery, the table
    change listeners that mark them dirty, and the subscriber fan-out."""

    def __init__(self, db, query_cache, telemetry=None,
                 resolver=None) -> None:
        self.db = db
        self.cache = query_cache
        self.federation = None  # set by server.py after cluster start
        self._resolve = resolver  # optional table-name resolver
        self._hop = telemetry.hop("query.standing") if telemetry else None
        self._lock = threading.Lock()
        self._queries: dict[str, StandingQuery] = {}
        self._subs: dict[str, Subscription] = {}
        # in-process push hooks fn(name, update) — the AlertEngine path.
        # Called on the refresher thread with the query's own lock held:
        # hooks must read the update payload, never registry.value_of().
        self.hooks: list = []
        self._listeners: dict[str, object] = {}  # table name -> callback
        self._dirty: set[str] = set()
        self._dirty_lock = threading.Lock()  # hot-path: keep it tiny
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._next_id = 0

    # -- kill-switch ---------------------------------------------------------

    @staticmethod
    def incremental_enabled() -> bool:
        return os.environ.get("DF_STANDING", "1") != "0"

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "StandingQueryRegistry":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._run,
                                            name="df-standing",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        with self._lock:
            subs = list(self._subs.values())
        for sub in subs:
            self.unsubscribe(sub.id)
        with self._lock:
            for name in list(self._queries):
                self._detach(self._queries.pop(name))

    # -- registration --------------------------------------------------------

    def _table(self, name: str):
        if self._resolve is not None:
            return self._resolve(name)
        for cand in (name, f"{name}.1s"):
            try:
                return self.db.table(cand)
            except KeyError:
                continue
        raise engine.QueryError(f"no such table {name!r}")

    def register(self, sql: str, *, name: str | None = None,
                 table: str | None = None, window_s: float = 0.0,
                 org_id=None, verify: bool = False) -> dict:
        select = S.parse(sql)
        table = self._table(table or select.table)
        if org_id is not None:
            if "org_id" not in table.columns:
                raise engine.QueryError(
                    f"table {table.name!r} has no org scoping")
            cond = S.BinOp("=", S.Col("org_id"), S.Lit(int(org_id)))
            select.where = (cond if select.where is None
                            else S.BinOp("AND", select.where, cond))
        if not name:
            name = f"q{abs(hash((table.name, normalize_sql(sql), org_id))) % 10 ** 8}"
        verify = verify or \
            os.environ.get("DF_STANDING_VERIFY", "0") == "1"
        sq = StandingQuery(name, table, sql, select, window_s, org_id,
                           verify)
        with self._lock:
            old = self._queries.get(name)
            self._queries[name] = sq
            if old is not None:
                self._detach(old)
            self._attach(table)
        self._refresh(sq)  # synchronous first fold: register returns gen 1
        return sq.summary()

    def unregister(self, name: str) -> bool:
        with self._lock:
            sq = self._queries.pop(name, None)
            if sq is None:
                return False
            self._detach(sq)
        return True

    def list(self) -> list[dict]:
        with self._lock:
            return [sq.summary() for sq in self._queries.values()]

    def get(self, name: str) -> StandingQuery | None:
        with self._lock:
            return self._queries.get(name)

    def value_of(self, name: str) -> float | None:
        """Current scalar value (first cell) of a standing query — the
        alert fast path: no query runs, the maintained result is exact
        as long as the change token hasn't moved (and a move re-pushes)."""
        sq = self.get(name)
        if sq is None or not sq.gen:
            return None
        with sq.lock:
            return _num(sq.rows[0][0]) if sq.rows else 0.0

    # -- table change hooks --------------------------------------------------

    def _attach(self, table) -> None:
        """Caller holds self._lock."""
        if table.name in self._listeners:
            return

        def _on_change(_t, _name=table.name, _self=self):
            with _self._dirty_lock:
                _self._dirty.add(_name)
            _self._wake.set()

        self._listeners[table.name] = _on_change
        table.add_listener(_on_change)

    def _detach(self, sq: StandingQuery) -> None:
        """Caller holds self._lock. Drops the table listener when the
        last query on that table goes away."""
        if any(q.table.name == sq.table.name
               for q in self._queries.values()):
            return
        fn = self._listeners.pop(sq.table.name, None)
        if fn is not None:
            sq.table.remove_listener(fn)

    # -- refresher -----------------------------------------------------------

    def _run(self) -> None:
        last_reap = time.monotonic()
        nap_until = 0.0
        while not self._stop.is_set():
            self._wake.wait(0.25)
            self._wake.clear()
            if self._stop.is_set():
                return
            now = time.monotonic()
            if now < nap_until:
                # duty-cycle / debounce nap: dirty marks stay queued.
                # Block on the STOP event, not the wake event — every
                # append sets the latter, and honoring it here would
                # turn an ingest burst into a refresher busy-loop.
                self._stop.wait(min(nap_until - now, 0.25))
                continue
            with self._dirty_lock:
                dirty, self._dirty = self._dirty, set()
            now = time.monotonic()
            fed = self.federation
            fed_live = fed is not None and fed.active()
            with self._lock:
                queries = list(self._queries.values())
            refreshed = False
            soonest = 0.0
            for sq in queries:
                due = sq.table.name in dirty
                if fed_live and now - sq.last_refresh >= FED_TICK_S:
                    due = True  # remote shards can move without local writes
                if not due:
                    continue
                gap = MIN_GAP_S - (now - sq.last_refresh)
                if gap > 0:
                    # debounce: re-mark and nap until the query is due
                    with self._dirty_lock:
                        self._dirty.add(sq.table.name)
                    soonest = gap if not soonest else min(soonest, gap)
                    continue
                try:
                    self._refresh(sq)
                    refreshed = True
                except Exception:
                    sq.counters["errors"] += 1
            spent = time.monotonic() - now
            if spent > 0.001:
                nap_until = time.monotonic() + min(
                    MAX_NAP_S, spent * (1.0 / REFRESH_BUDGET - 1.0))
            elif not refreshed and soonest:
                nap_until = now + soonest
            if now - last_reap >= 30.0:
                last_reap = now
                self._reap_idle(now)

    def _reap_idle(self, now: float) -> None:
        with self._lock:
            stale = [s.id for s in self._subs.values()
                     if now - s.last_seen > IDLE_REAP_S]
        for sid in stale:
            self.unsubscribe(sid)

    # -- the incremental fold ------------------------------------------------

    def _window(self, sq: StandingQuery):
        """(bucket_range, windowed_select) for this refresh, anchored on
        the newest DATA bucket (deterministic: the window slides only
        when data arrives, and arrival always marks the query dirty)."""
        if not sq.window_s:
            return None, sq.select
        _wm, marks, _wide, div = sq.table.bucket_marks()
        if div <= 0 or not marks:
            return None, sq.select
        hi_b = max(marks) + 1
        lo_b = hi_b - max(1, math.ceil(sq.window_s / 60.0))
        tc = sq.table._time_col
        sel = sq.select
        rng = S.BinOp("AND",
                      S.BinOp(">=", S.Col(tc), S.Lit(int(lo_b * div))),
                      S.BinOp("<", S.Col(tc), S.Lit(int(hi_b * div))))
        where = rng if sel.where is None else \
            S.BinOp("AND", sel.where, rng)
        wsel = S.Select(items=sel.items, table=sel.table, where=where,
                        group_by=sel.group_by, having=sel.having,
                        order_by=sel.order_by, limit=sel.limit)
        return (lo_b, hi_b), wsel

    def _refresh(self, sq: StandingQuery) -> None:
        with sq.lock:
            t0 = time.perf_counter_ns()
            fed = self.federation
            if fed is not None and fed.active():
                self._refresh_federated(sq, t0)
                return
            table = sq.table
            tok = change_token(table)  # BEFORE folding: stale-safe
            if sq.gen and tok == sq.token:
                sq.counters["skipped"] += 1
                sq.last_refresh = time.monotonic()
                return
            brange, wsel = self._window(sq)
            res, mode = None, "full"
            if self.incremental_enabled():
                res, stats = self.cache.standing_fold(
                    table, sq.sql, select=sq.select,
                    extra_key=sq.extra_key, bucket_range=brange)
                if res is not None:
                    mode = "incremental"
                    sq.counters["buckets_folded"] += stats["buckets"]
                    sq.counters["buckets_reused"] += \
                        stats["bucket_hits"] + stats["dist_hits"]
                    sq.counters["buckets_scanned"] += stats["scanned"]
            if res is None:
                res = engine.execute(table, wsel)
            if sq.verify and mode == "incremental" \
                    and change_token(table) == tok:
                # equivalence assertion, skipped when a write raced the
                # fold (the race re-marks us dirty; next refresh retries)
                ref = engine.execute(table, wsel)
                if self._canon(res) != self._canon(ref):
                    sq.counters["verify_failures"] += 1
                    res = ref
            sq.counters["incremental" if mode == "incremental"
                        else "full"] += 1
            self._finish(sq, res, tok, mode, t0)

    def _refresh_federated(self, sq: StandingQuery, t0: int) -> None:
        """Federated refresh: the coordinator's if_state machinery means
        only shards whose change token moved recompute; an all-unchanged
        tick is a warm cache hit (zero shard work). Windows are not
        pushed down federated — register windowed SQL text instead."""
        fed = self.federation
        res, info = fed.sql_query(sq.table, sq.select, sq.sql,
                                  org_id=sq.org)
        sq.counters["fed_refreshes"] += 1
        if isinstance(info, dict):
            if info.get("cache") == "warm":
                sq.counters["fed_warm"] += 1
            sq.counters["fed_shards_unchanged"] += \
                int(info.get("shards_unchanged", 0))
            sq.counters["fed_shards_refetched"] += \
                int(info.get("shards_refetched", 0))
        self._finish(sq, res, None, "federated", t0)

    @staticmethod
    def _canon(res: engine.QueryResult) -> str:
        return json.dumps(res.to_dict(), sort_keys=True, default=str)

    def _finish(self, sq: StandingQuery, res: engine.QueryResult,
                tok, mode: str, t0: int) -> None:
        """Compare, bump the generation on change, publish the delta.
        Caller holds sq.lock."""
        sq.counters["refreshes"] += 1
        sq.last_refresh = time.monotonic()
        sq.last_ms = (time.perf_counter_ns() - t0) / 1e6
        new_rows = json.loads(self._canon(res))["values"]
        cols = list(res.columns)
        sq.token = tok
        if sq.gen and new_rows == sq.rows and cols == sq.columns:
            sq.counters["unchanged"] += 1
            return
        delta = self._delta(sq.rows if sq.gen else [], new_rows)
        sq.gen += 1
        sq.rows, sq.columns = new_rows, cols
        self._publish(sq, {
            "query": sq.name, "gen": sq.gen, "mode": mode,
            "columns": cols, "rows": new_rows, "delta": delta,
            "ts_ns": time.time_ns(),
            "refresh_ms": round(sq.last_ms, 3)})

    @staticmethod
    def _delta(old: list[list], new: list[list]) -> dict:
        """Multiset row diff: a changed aggregate row is removed(old) +
        added(new); group keys never need interpreting here."""
        from collections import Counter

        def keyed(rows):
            return Counter(json.dumps(r, sort_keys=True, default=str)
                           for r in rows)

        co, cn = keyed(old), keyed(new)
        added = [json.loads(k) for k, n in (cn - co).items() for _ in
                 range(n)]
        removed = [json.loads(k) for k, n in (co - cn).items() for _ in
                   range(n)]
        return {"added": added, "removed": removed}

    # -- push surface --------------------------------------------------------

    def subscribe(self, names: list[str] | None = None) -> dict:
        """New subscriber. The current state of every matched query is
        enqueued as its generation's snapshot — the baseline delivery
        for exactly-once-per-(subscriber, generation) downstream."""
        with self._lock:
            self._next_id += 1
            sid = f"sub-{self._next_id}"
            sub = Subscription(sid, set(names) if names else None)
            self._subs[sid] = sub
            snaps = [sq for sq in self._queries.values()
                     if sub.wants(sq.name) and sq.gen]
            for sq in snaps:
                self._enqueue(sub, {
                    "query": sq.name, "gen": sq.gen, "mode": "snapshot",
                    "columns": sq.columns, "rows": sq.rows,
                    "delta": {"added": sq.rows, "removed": []},
                    "ts_ns": time.time_ns(), "refresh_ms": 0.0})
        return {"subscriber": sid,
                "queries": sorted(sq.name for sq in snaps)}

    def unsubscribe(self, sid: str) -> bool:
        with self._lock:
            sub = self._subs.pop(sid, None)
        if sub is None:
            return False
        with sub.cond:
            sub.closed = True
            stranded = len(sub.pending)
            sub.pending.clear()
            sub.cond.notify_all()
        if stranded and self._hop is not None:
            self._hop.account(dropped=stranded, reason="closed")
        return True

    def _publish(self, sq: StandingQuery, update: dict) -> None:
        with self._lock:
            subs = [s for s in self._subs.values() if s.wants(sq.name)]
            for sub in subs:
                self._enqueue(sub, update)
            hooks = list(self.hooks)
        for fn in hooks:  # outside the registry lock; sq.lock still held
            try:
                fn(sq.name, update)
            except Exception:
                pass

    def _enqueue(self, sub: Subscription, update: dict) -> None:
        dropped = 0
        with sub.cond:
            if sub.closed:
                return
            sub.pending.append(update)
            while len(sub.pending) > MAX_PENDING:
                sub.pending.popleft()
                dropped += 1
            sub.cond.notify_all()
        if self._hop is not None:
            self._hop.account(emitted=1, dropped=dropped,
                              reason="subscriber_lag" if dropped else "")

    def poll(self, sid: str, timeout_s: float = 25.0,
             max_items: int = 64) -> dict:
        """Long-poll drain: blocks until at least one update (or the
        timeout), returns up to max_items in generation order."""
        with self._lock:
            sub = self._subs.get(sid)
        if sub is None:
            return {"updates": [], "closed": True}
        timeout_s = max(0.0, min(float(timeout_s), 60.0))
        out: list[dict] = []
        with sub.cond:
            sub.last_seen = time.monotonic()
            if not sub.pending and not sub.closed and timeout_s:
                sub.cond.wait_for(
                    lambda: sub.pending or sub.closed, timeout=timeout_s)
            while sub.pending and len(out) < max(1, int(max_items)):
                out.append(sub.pending.popleft())
            sub.delivered += len(out)
            sub.last_seen = time.monotonic()
            closed = sub.closed
        if out and self._hop is not None:
            self._hop.account(delivered=len(out))
        return {"updates": out, "closed": closed}

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            queries = {name: sq.summary()
                       for name, sq in self._queries.items()}
            subs = {s.id: {"pending": len(s.pending),
                           "delivered": s.delivered,
                           "queries": (sorted(s.names) if s.names
                                       else None)}
                    for s in self._subs.values()}
        out = {"incremental": self.incremental_enabled(),
               "queries": queries, "subscribers": subs}
        if self._hop is not None:
            out["ledger"] = self._hop.snapshot()
        return out
