"""Shared morsel scan worker pool (query/engine.py + server RollupJob).

Reference analog: ClickHouse's query thread pool scanning MergeTree
parts in parallel. One process-wide pool, sized from ``os.cpu_count()``
with a ``DF_QUERY_THREADS`` override re-read on every acquisition — the
stress sweep (and an operator tuning a live server) can change the
degree between queries and the pool resizes in place. 1 means "no pool":
callers get None and run today's serial path.

Nested-parallelism guard: work dispatched through the pool runs with a
thread-local ``in_worker`` flag set. The engine checks it before
planning a parallel scan, so a pool task that itself executes a query
(RollupJob stages, query-cache bucket refills) degrades to the serial
path instead of deadlocking on the pool it is occupying.

The actual parallelism comes from the GIL-released native kernels
(qexec.cpp group/aggregate, zlib decompress, numpy ufuncs over mmap'd
blocks) — pure-Python morsels would serialize on the GIL and this pool
would only add overhead, which is exactly what the engine's degree cost
model learns and avoids.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor

_LOCAL = threading.local()
_LOCK = threading.Lock()
_POOL: ThreadPoolExecutor | None = None
_POOL_THREADS = 0
_BUSY = 0
_DISPATCHED = 0
_QTRACE = None


def _qtrace():
    """qtrace module, bound once (the import-inside-the-hot-loop lookup
    was measurable at one call per morsel)."""
    global _QTRACE
    if _QTRACE is None:
        from deepflow_tpu.query import qtrace
        _QTRACE = qtrace
    return _QTRACE


def configured_threads() -> int:
    """Pool size: DF_QUERY_THREADS override, else os.cpu_count()."""
    env = os.environ.get("DF_QUERY_THREADS", "").strip()
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return os.cpu_count() or 1


def in_worker() -> bool:
    return getattr(_LOCAL, "in_worker", False)


def get_pool() -> "ScanPool | None":
    """The shared pool at the currently-configured size, or None when
    the configuration says serial (1 thread) or the caller is already a
    pool worker (nested fan-out would deadlock)."""
    n = configured_threads()
    if n <= 1 or in_worker():
        return None
    global _POOL, _POOL_THREADS
    with _LOCK:
        if _POOL is None or _POOL_THREADS != n:
            if _POOL is not None:
                # in-flight tasks finish on the old threads; new work
                # lands on the resized pool
                _POOL.shutdown(wait=False)
            _POOL = ThreadPoolExecutor(
                max_workers=n, thread_name_prefix="df-scan")
            _POOL_THREADS = n
    return ScanPool(_POOL, n)


def stats() -> dict:
    """Health view: configured size + live occupancy."""
    with _LOCK:
        return {"threads": _POOL_THREADS, "busy": _BUSY,
                "dispatched": _DISPATCHED}


class ScanPool:
    """Thin ordered-map facade over the shared executor."""

    __slots__ = ("_ex", "threads")

    def __init__(self, ex: ThreadPoolExecutor, threads: int) -> None:
        self._ex = ex
        self.threads = threads

    @staticmethod
    def _run(fn, item, tbuf=None, anchor=None):
        global _BUSY
        _LOCAL.in_worker = True
        with _LOCK:
            _BUSY += 1
        try:
            if tbuf is None:
                return fn(item)
            # re-attach the submitting query's trace buffer: segcache
            # fetches and prune decisions inside a morsel/bucket scan
            # then land in the right span tree.  Inlined thread-local
            # swap rather than qtrace.use_buf — this runs once per
            # MORSEL, and at default morsel sizing the ctx-manager +
            # per-task anchor allocation alone were a measurable slice
            # of the query-path overhead budget.
            tls = _qtrace()._tls
            prev_buf = getattr(tls, "buf", None)
            prev_span = getattr(tls, "span", None)
            tls.buf = tbuf
            tls.span = anchor
            try:
                return fn(item)
            finally:
                tls.buf = prev_buf
                tls.span = prev_span
        finally:
            with _LOCK:
                _BUSY -= 1
            _LOCAL.in_worker = False

    def map(self, fn, items: list) -> list:
        """fn over items on the pool; results in input order. The first
        task raising propagates (after every future resolves, so no task
        outlives the call and touches freed state)."""
        global _DISPATCHED
        with _LOCK:
            _DISPATCHED += len(items)
        qtrace = _qtrace()
        tbuf = qtrace.current_buf()
        anchor = None
        if tbuf is not None:
            tsid = qtrace.current_span_id()
            if tsid:
                # one anchor shared by every task of this map call:
                # span() only reads .span_id off it for parenting, and
                # stray annotate()/bump() land in an attrs dict nobody
                # records
                anchor = qtrace.Span.__new__(qtrace.Span)
                anchor.span_id = tsid
                anchor.attrs = {}
        futs = [self._ex.submit(self._run, fn, it, tbuf, anchor)
                for it in items]
        out, err = [], None
        for f in futs:
            try:
                out.append(f.result())
            except BaseException as e:  # noqa: BLE001 — re-raised below
                if err is None:
                    err = e
        if err is not None:
            raise err
        return out
