"""Shard-result wire format: one codec frame, columnar payload.

Layout inside a MessageType.SHARD_RESULT frame (zlib handled by the
frame layer for payloads > 512B):

    [u32 meta_len][meta json][col bytes]...

meta = {"kind": "table", "columns": [...], "encodings": [...], "n": N,
        "extra": {...}} for row/column data — numeric columns travel as
raw little-endian float64/int64 arrays (8 bytes/row, no JSON number
parsing on the hot merge path), everything else as a JSON list. Any
non-tabular object (agg partials, peer lists, span dicts) falls back to
{"kind": "json"} with the object as the JSON body. Both sides derive
the column layout from the same parsed query, so the encodings list is
all the schema negotiation there is.
"""

from __future__ import annotations

import json
import struct

import numpy as np

from deepflow_tpu.codec import (FrameHeader, MessageType, decode_frame,
                                encode_frame)

_LEN = struct.Struct(">I")

# per-column encodings
_F64 = "f64"     # raw little-endian float64 bytes
_I64 = "i64"     # raw little-endian int64 bytes
_JSON = "json"   # JSON list (strings, mixed, nested)


class WireError(Exception):
    pass


# -- query-trace context propagation -----------------------------------------
# The trace context of a federated query rides the scatter body as one
# small JSON dict under this key; shards that predate query tracing
# ignore it (unknown body keys were always tolerated), shards that know
# it adopt the context so their spans stitch into the coordinator's
# trace (query/qtrace.py).

QTRACE_KEY = "qtrace"


def inject_ctx(body: dict) -> dict:
    """Return ``body`` with the calling thread's active trace context
    attached (a copy — scatter bodies are shared across peers); the
    body passes through untouched when no trace is active."""
    from deepflow_tpu.query import qtrace
    ctx = qtrace.ctx_for_wire()
    if ctx is None:
        return body
    out = dict(body)
    out[QTRACE_KEY] = ctx
    return out


def extract_ctx(body: dict) -> dict | None:
    ctx = body.get(QTRACE_KEY) if isinstance(body, dict) else None
    return ctx if isinstance(ctx, dict) else None


def _has_ndarray(obj) -> bool:
    if isinstance(obj, np.ndarray):
        return True
    if isinstance(obj, dict):
        return any(_has_ndarray(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return any(_has_ndarray(v) for v in obj)
    return False


def _encode_jsonb(obj) -> bytes:
    """"jsonb" kind: arbitrary JSON structure whose embedded ndarrays
    travel as dtype-preserving raw blobs appended after the JSON body
    ({"__bin__": i} placeholders mark the splice points). Carries the
    encoded agg partials — uint32 dictionary-id columns stay 4 bytes per
    group instead of JSON-quoted strings. Only emitted when a payload
    actually contains ndarrays, so pre-encoding peers never see it."""
    blobs: list[np.ndarray] = []

    def strip(o):
        if isinstance(o, np.ndarray):
            blobs.append(np.ascontiguousarray(o))
            return {"__bin__": len(blobs) - 1}
        if isinstance(o, dict):
            return {k: strip(v) for k, v in o.items()}
        if isinstance(o, (list, tuple)):
            return [strip(v) for v in o]
        if isinstance(o, np.generic):
            return o.item()
        return o

    body = strip(obj)
    meta = {"kind": "jsonb", "obj": body,
            "blobs": [[a.dtype.str, int(a.size)] for a in blobs]}
    mb = json.dumps(meta, separators=(",", ":")).encode()
    return _LEN.pack(len(mb)) + mb + b"".join(a.tobytes() for a in blobs)


def _decode_jsonb(meta: dict, buf: memoryview):
    arrays: list[np.ndarray] = []
    off = 0
    for dt, size in meta.get("blobs", []):
        dtype = np.dtype(dt)
        end = off + dtype.itemsize * int(size)
        arrays.append(np.frombuffer(buf[off:end], dtype=dtype))
        off = end

    def restore(o):
        if isinstance(o, dict):
            if len(o) == 1 and "__bin__" in o:
                return arrays[int(o["__bin__"])]
            return {k: restore(v) for k, v in o.items()}
        if isinstance(o, list):
            return [restore(v) for v in o]
        return o

    return restore(meta.get("obj"))


def _encode_table(obj: dict) -> bytes:
    columns = list(obj["columns"])
    values = obj["values"]
    n = len(values)
    encodings: list[str] = []
    blobs: list[bytes] = []
    for ci in range(len(columns)):
        col = [row[ci] for row in values]
        if n and all(isinstance(v, bool) is False and
                     isinstance(v, (int, float)) for v in col):
            if all(isinstance(v, int) and -(1 << 62) < v < (1 << 62)
                   for v in col):
                encodings.append(_I64)
                blobs.append(np.asarray(col, dtype="<i8").tobytes())
            else:
                encodings.append(_F64)
                blobs.append(np.asarray(col, dtype="<f8").tobytes())
        else:
            encodings.append(_JSON)
            b = json.dumps(col, separators=(",", ":")).encode()
            blobs.append(_LEN.pack(len(b)) + b)
    # every top-level key besides the column data rides along in meta
    # (e.g. a rows-partial's {"kind": "rows"} marker) and is restored on
    # decode — the table layout is an encoding, not a schema filter
    extra = {k: v for k, v in obj.items()
             if k not in ("columns", "values")}
    meta = {"kind": "table", "columns": columns, "encodings": encodings,
            "n": n, "extra": extra}
    mb = json.dumps(meta, separators=(",", ":")).encode()
    return _LEN.pack(len(mb)) + mb + b"".join(blobs)


def _decode_table(meta: dict, buf: memoryview) -> dict:
    n = int(meta["n"])
    cols: list[list] = []
    off = 0
    for enc in meta["encodings"]:
        if enc in (_F64, _I64):
            dtype = "<f8" if enc == _F64 else "<i8"
            end = off + 8 * n
            cols.append(np.frombuffer(buf[off:end], dtype=dtype).tolist())
            off = end
        elif enc == _JSON:
            (blen,) = _LEN.unpack(buf[off:off + 4])
            off += 4
            cols.append(json.loads(bytes(buf[off:off + blen])))
            off += blen
        else:
            raise WireError(f"unknown column encoding {enc!r}")
    values = [list(row) for row in zip(*cols)] if cols and n else []
    out = {"columns": list(meta["columns"]), "values": values}
    out.update(meta.get("extra") or {})
    return out


def encode_result(obj, shard_id: int = 0) -> bytes:
    """Serialize one shard response into a SHARD_RESULT frame."""
    if (isinstance(obj, dict) and "columns" in obj and "values" in obj
            and isinstance(obj.get("values"), list)):
        payload = _encode_table(obj)
    elif _has_ndarray(obj):
        payload = _encode_jsonb(obj)
    else:
        b = json.dumps({"kind": "json", "obj": obj},
                       separators=(",", ":")).encode()
        payload = _LEN.pack(len(b)) + b
    return encode_frame(
        FrameHeader(MessageType.SHARD_RESULT, agent_id=shard_id & 0xFFFF),
        payload)


def decode_result(frame: bytes):
    """Inverse of encode_result -> (obj, shard_id)."""
    header, payload, consumed = decode_frame(frame)
    if consumed == 0:
        raise WireError("short shard-result frame")
    if header.msg_type != MessageType.SHARD_RESULT:
        raise WireError(f"unexpected frame type {header.msg_type}")
    view = memoryview(payload)
    (mlen,) = _LEN.unpack(view[:4])
    meta = json.loads(bytes(view[4:4 + mlen]))
    if meta.get("kind") == "table":
        return _decode_table(meta, view[4 + mlen:]), header.agent_id
    if meta.get("kind") == "jsonb":
        return _decode_jsonb(meta, view[4 + mlen:]), header.agent_id
    return meta.get("obj"), header.agent_id


def encode_cache_partial(obj, shard_id: int = 0) -> bytes:
    """Serialize one distributed partial-cache exchange (request ack or
    warm bucket response) into a CACHE_PARTIAL frame. Always the jsonb
    form — encoded per-bucket partials are exactly the ndarray-bearing
    payloads jsonb exists for, and the kind doubles as the type check
    (a stray SHARD_RESULT on this path must fail loudly)."""
    return encode_frame(
        FrameHeader(MessageType.CACHE_PARTIAL, agent_id=shard_id & 0xFFFF),
        _encode_jsonb(obj))


def decode_cache_partial(frame: bytes):
    """Inverse of encode_cache_partial -> (obj, shard_id)."""
    header, payload, consumed = decode_frame(frame)
    if consumed == 0:
        raise WireError("short cache-partial frame")
    if header.msg_type != MessageType.CACHE_PARTIAL:
        raise WireError(f"unexpected frame type {header.msg_type}")
    view = memoryview(payload)
    (mlen,) = _LEN.unpack(view[:4])
    meta = json.loads(bytes(view[4:4 + mlen]))
    if meta.get("kind") != "jsonb":
        raise WireError(f"unexpected cache-partial kind {meta.get('kind')!r}")
    return _decode_jsonb(meta, view[4 + mlen:]), header.agent_id
