"""Remote shard execution: POST /v1/shard/exec with timeout/retry/hedge.

Each logical shard call is one HopLedger item on its fan-out hop:
emitted when the scatter starts, delivered on any successful response,
dropped (reason timeout|error) when every attempt fails — so
``emitted == delivered + dropped`` holds across a quiesced cluster and
`make cluster-check` can assert ledger balance over federated queries.
Retries and the hedged second request are attempts WITHIN one item,
tracked in client stats only.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait

from deepflow_tpu.cluster import wire
from deepflow_tpu.cluster.membership import Peer

log = logging.getLogger("df.cluster")


class ShardCallError(Exception):
    def __init__(self, msg: str, reason: str = "error") -> None:
        super().__init__(msg)
        self.reason = reason  # "timeout" | "error"


class ShardClient:
    """One peer's /v1/shard/exec client."""

    def __init__(self, addr: str, timeout_s: float = 5.0,
                 retries: int = 1, hedge_delay_s: float = 0.25,
                 api_token: str | None = None) -> None:
        self.addr = addr
        self.timeout_s = timeout_s
        self.retries = retries            # extra attempts after the first
        self.hedge_delay_s = hedge_delay_s
        self.api_token = api_token
        self.stats = {"attempts": 0, "hedges": 0, "errors": 0}
        self._lock = threading.Lock()

    def _attempt(self, body: dict, deadline: float):
        budget = deadline - time.monotonic()
        if budget <= 0:
            raise ShardCallError(f"{self.addr}: deadline exhausted",
                                 reason="timeout")
        with self._lock:
            self.stats["attempts"] += 1
        headers = {"Content-Type": "application/json"}
        if self.api_token:
            headers["X-DF-Token"] = self.api_token
        req = urllib.request.Request(
            f"http://{self.addr}/v1/shard/exec",
            data=json.dumps(body).encode(), headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=budget) as resp:
                obj, _sid = wire.decode_result(resp.read())
                return obj
        except urllib.error.HTTPError as e:
            detail = e.read()[:200].decode(errors="replace")
            raise ShardCallError(
                f"{self.addr}: HTTP {e.code} {detail}") from None
        except (TimeoutError, OSError) as e:
            reason = ("timeout" if isinstance(e, TimeoutError)
                      or "timed out" in str(e).lower() else "error")
            raise ShardCallError(f"{self.addr}: {e}", reason=reason) \
                from None

    def call(self, body: dict, pool: ThreadPoolExecutor | None = None):
        """One logical call: bounded retries, plus a hedged second
        attempt racing the first once hedge_delay_s passes without an
        answer (slow-shard tail cut, reference: hedged ClickHouse
        connections in the querier)."""
        deadline = time.monotonic() + self.timeout_s
        last: ShardCallError | None = None
        for _ in range(1 + max(0, self.retries)):
            if pool is None or self.hedge_delay_s <= 0:
                try:
                    return self._attempt(body, deadline)
                except ShardCallError as e:
                    last = e
                    continue
            primary = pool.submit(self._attempt, body, deadline)
            done, _ = wait([primary], timeout=self.hedge_delay_s)
            futures = [primary]
            if not done:
                with self._lock:
                    self.stats["hedges"] += 1
                futures.append(pool.submit(self._attempt, body, deadline))
            pending = set(futures)
            while pending:
                done, pending = wait(
                    pending, timeout=max(0.0, deadline - time.monotonic()),
                    return_when=FIRST_COMPLETED)
                if not done:   # overall deadline hit; attempts self-expire
                    break
                for f in done:
                    try:
                        result = f.result()
                    except ShardCallError as e:
                        last = e
                        continue
                    for p in pending:
                        p.cancel()
                    return result
            if last is None:
                last = ShardCallError(f"{self.addr}: deadline exhausted",
                                      reason="timeout")
        with self._lock:
            self.stats["errors"] += 1
        raise last


class FanOut:
    """Scatter one op over the alive remote peers, gather with a
    missing_shards annotation instead of an error (degraded mode)."""

    def __init__(self, telemetry=None, timeout_s: float = 5.0,
                 retries: int = 1, hedge_delay_s: float = 0.25,
                 api_token: str | None = None,
                 max_workers: int = 16) -> None:
        self.telemetry = telemetry
        self.timeout_s = timeout_s
        self.retries = retries
        self.hedge_delay_s = hedge_delay_s
        self.api_token = api_token
        self._clients: dict[str, ShardClient] = {}
        self._last_used: dict[str, float] = {}
        self.evicted = 0
        self._lock = threading.Lock()
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="df-fanout")
        # attempts run in their own pool: if per-shard calls and their
        # retry/hedge attempts shared one saturated pool, the outer
        # futures would starve the inner ones into a deadline stall
        self._attempt_pool = ThreadPoolExecutor(
            max_workers=2 * max_workers, thread_name_prefix="df-fanout-io")

    def client(self, addr: str) -> ShardClient:
        with self._lock:
            c = self._clients.get(addr)
            if c is None:
                c = self._clients[addr] = ShardClient(
                    addr, timeout_s=self.timeout_s, retries=self.retries,
                    hedge_delay_s=self.hedge_delay_s,
                    api_token=self.api_token)
            self._last_used[addr] = time.monotonic()
            return c

    def prune(self, active_addrs: set[str] | list[str],
              ttl_s: float = 300.0) -> int:
        """Evict clients whose peer left the directory, or that no
        scatter touched for ttl_s — long-lived coordinators would
        otherwise accumulate one cached client per address ever seen
        across rebalances. Safe mid-flight: a scatter already holds its
        ShardClient reference, and clients keep no open sockets between
        requests."""
        horizon = time.monotonic() - ttl_s
        active = set(active_addrs)
        with self._lock:
            stale = [a for a in self._clients
                     if a not in active
                     or self._last_used.get(a, 0.0) < horizon]
            for a in stale:
                del self._clients[a]
                self._last_used.pop(a, None)
            self.evicted += len(stale)
        if stale:
            log.info("cluster: evicted %d shard client(s): %s",
                     len(stale), ", ".join(sorted(stale)))
        return len(stale)

    def scatter(self, peers: list[Peer], body: dict,
                hop_name: str) -> tuple[dict[int, object], list[int]]:
        """-> ({shard_id: result}, missing_shard_ids)."""
        hop = (self.telemetry.hop(hop_name)
               if self.telemetry is not None else None)
        if not peers:
            return {}, []
        if hop is not None:
            hop.account(emitted=len(peers))
        t0 = time.monotonic_ns()
        # trace propagation: the coordinator's context rides the shared
        # body; each peer call gets a client span recorded from the
        # fan-out worker thread (re-attached to the submitting query's
        # trace buffer — pool threads don't inherit thread-locals)
        from deepflow_tpu.query import qtrace
        tbuf = qtrace.current_buf()
        tsid = qtrace.current_span_id()

        def _traced_call(client, b, peer):
            if tbuf is None:
                return client.call(b, self._attempt_pool)
            with qtrace.use_buf(tbuf, tsid):
                with qtrace.span("shard.call", shard=peer.shard_id,
                                 addr=peer.addr, op=str(b.get("op", ""))):
                    # inject inside the client span so the shard-side
                    # root parents under ITS OWN shard.call, not the
                    # shared scatter span
                    return client.call(wire.inject_ctx(b),
                                       self._attempt_pool)

        futs = {self._pool.submit(_traced_call, self.client(p.addr),
                                  body, p): p for p in peers}
        results: dict[int, object] = {}
        missing: list[int] = []
        for fut, peer in futs.items():
            try:
                results[peer.shard_id] = fut.result(
                    timeout=self.timeout_s * (2 + self.retries))
            except ShardCallError as e:
                missing.append(peer.shard_id)
                log.warning("cluster: shard %d (%s) dropped from %s: %s",
                            peer.shard_id, peer.addr, hop_name, e)
                if hop is not None:
                    hop.account(dropped=1, reason=e.reason)
            except Exception as e:   # future timeout / unexpected
                missing.append(peer.shard_id)
                log.warning("cluster: shard %d (%s) failed on %s: %s",
                            peer.shard_id, peer.addr, hop_name, e)
                if hop is not None:
                    hop.account(dropped=1, reason="error")
        if results and hop is not None:
            hop.account(delivered=len(results),
                        wait_ns=time.monotonic_ns() - t0)
        return results, sorted(missing)

    def stats(self) -> dict:
        with self._lock:
            return {addr: dict(c.stats)
                    for addr, c in sorted(self._clients.items())}

    def close(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)
        self._attempt_pool.shutdown(wait=False, cancel_futures=True)
