"""Cluster federation: N servers answering as one querier.

Reference analog: the multi-ingester DeepFlow deployment where the
querier fans a query out over every ClickHouse shard and merges
(server/querier engine + ingester sharding). Here each server owns a
shard-local store.Database; this package adds membership gossip, a
framed columnar result wire format, a retry/hedge remote-execution
client, and the scatter-gather merge used by the querier.
"""

from deepflow_tpu.cluster.membership import (ClusterMembership, Peer,
                                             PeerDirectory)
from deepflow_tpu.cluster.remote import FanOut, ShardCallError, ShardClient
from deepflow_tpu.cluster.wire import decode_result, encode_result

__all__ = [
    "ClusterMembership", "Peer", "PeerDirectory",
    "FanOut", "ShardCallError", "ShardClient",
    "encode_result", "decode_result",
]
