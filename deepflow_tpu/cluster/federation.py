"""Scatter-gather coordinator: one querier answers for N sharded servers.

Merge semantics (docs/CLUSTER.md spells out the contract):

- DF-SQL: partial-aggregate push-down. Each shard runs
  engine.execute_partial (groups keyed by DECODED values — SmartEncoding
  ids are shard-local and never merged); the coordinator reduces with
  engine.merge_partials. Exact for SUM/COUNT/MIN/MAX/AVG/LAST/
  COUNT(DISTINCT); PERCENTILE merges histogram sketches (~2% error).
- PromQL: Thanos-style raw-selector fan-out. Only fetch_raw is
  federated (via the db-shim below); the whole AST evaluates at the
  coordinator, so every PromQL function stays EXACT.
- Tempo search: shards return per-trace scan partials; one trace's spans
  may land on many shards, so trace-level start/end/duration exist only
  after the merge — duration filters and the limit apply here, never
  shard-side.
- Trace assembly / flame graphs: span-dict union (dedup by
  (span_id, start_ns, flow_id) in build_trace_from_spans) and
  stack-string sums.
- Degraded mode: a dead or timed-out shard never fails the query; its
  ids land in the "missing_shards" annotation of the partial result.
- Replicated mode (a HashRing is active): every scatter ships the ring
  snapshot + the alive set, each shard answers from its claim-filtered
  view (exactly one alive owner reports each row), and a shard failure
  triggers ONE re-scatter with the shrunk alive set so a dead primary's
  rows get promoted to the surviving replica. When every dead shard is
  covered (dead ⊆ ring members, |dead| ≤ R−1) the result is EXACT:
  missing_shards stays empty and the dead ids land in covered_shards.
"""

from __future__ import annotations

import time
from collections import OrderedDict

import numpy as np

from deepflow_tpu.cluster.dictsync import DictSync, DictSyncError
from deepflow_tpu.cluster.hashring import ClaimDbView, HashRing
from deepflow_tpu.cluster.membership import (DEFAULT_TTL_S,
                                             ClusterMembership, Peer)
from deepflow_tpu.cluster.remote import FanOut, ShardCallError
from deepflow_tpu.query import cache, engine, promql, qtrace
from deepflow_tpu.query import sql as qsql
from deepflow_tpu.query.flamegraph import merge_stack_values


def merge_tempo_partials(parts: list[list[dict]]) -> list[dict]:
    """Union per-shard Tempo scan partials by traceID.

    Per trace: start = min, end = max (span sets are disjoint-ish across
    shards), _matched OR (a tag may match on any shard's spans), root
    fields from whichever shard saw the earliest span (_root_t)."""
    by_id: dict[str, dict] = {}
    for part in parts:
        for tr in part:
            cur = by_id.get(tr["traceID"])
            if cur is None:
                by_id[tr["traceID"]] = dict(tr)
                continue
            if tr.get("_root_t", 0) < cur.get("_root_t", 0):
                cur["rootServiceName"] = tr.get("rootServiceName", "")
                cur["rootTraceName"] = tr.get("rootTraceName", "")
                cur["_root_t"] = tr.get("_root_t", 0)
            cur["_start_ns"] = min(cur["_start_ns"], tr["_start_ns"])
            cur["_end_ns"] = max(cur["_end_ns"], tr["_end_ns"])
            cur["spanCount"] = cur.get("spanCount", 0) + tr.get(
                "spanCount", 0)
            cur["_matched"] = cur.get("_matched", False) or tr.get(
                "_matched", False)
    return list(by_id.values())


class _FederatedPromDb:
    """Database shim handed to promql.evaluate: intercepts fetch_raw
    (the promql_fetch_raw hook) and merges local + remote RawSeries by
    full label set. Everything else (table/tables for metadata paths)
    delegates to the local store. One instance per request — it
    accumulates that request's missing_shards."""

    def __init__(self, coord: "FederationCoordinator") -> None:
        self._coord = coord
        self._db = coord.db
        self.missing_shards: set[int] = set()
        self.fed_info: dict = {}

    def table(self, name: str):
        return self._db.table(name)

    def tables(self) -> list[str]:
        return self._db.tables()

    def __getattr__(self, name: str):
        return getattr(self._db, name)

    def promql_fetch_raw(self, vs, lo_s: float, hi_s: float):
        results, info, db = self._coord.scatter_claim(
            {"op": "promql_raw", "metric": vs.metric,
             "matchers": [list(m) for m in vs.matchers],
             "lo_s": float(lo_s), "hi_s": float(hi_s)},
            hop_name="cluster.promql")
        self.missing_shards.update(info["missing_shards"])
        self.fed_info = info
        local_unknown = False
        try:
            local = promql.fetch_raw(db, vs, lo_s, hi_s)
        except promql.UnknownMetricError:
            local, local_unknown = [], True
        remote_known = False
        merged: dict[tuple, promql.RawSeries] = {}

        def fold(series_list):
            for s in series_list:
                key = tuple(sorted((k, str(v))
                            for k, v in s.labels.items()))
                cur = merged.get(key)
                if cur is None:
                    merged[key] = s
                else:
                    t = np.concatenate([cur.t, s.t])
                    v = np.concatenate([cur.v, s.v])
                    order = np.argsort(t, kind="stable")
                    cur.t, cur.v = t[order], v[order]

        fold(local)
        for res in results.values():
            if res.get("unknown"):
                continue
            remote_known = True
            fold([promql.RawSeries(
                labels=d["labels"],
                t=np.asarray(d["t"], dtype=np.int64),
                v=np.asarray(d["v"], dtype=np.float64),
                counter=bool(d["counter"])) for d in res["series"]])
        if local_unknown and not remote_known and not self.missing_shards:
            # only a clean miss is an error: with a shard unreachable the
            # metric may live exactly there, and the degraded contract
            # says partial-and-annotated, never a 500
            raise promql.UnknownMetricError(
                f"unknown metric {vs.metric!r} on every shard")
        return list(merged.values())


class FederationCoordinator:
    """Ties membership + FanOut + the per-signal merge steps together.
    Every public method returns (result, fed_info) where fed_info is
    {"shards": total answering, "missing_shards": [ids]} — the degraded
    -mode contract: partial data is annotated, never a 500."""

    def __init__(self, db, membership: ClusterMembership,
                 fanout: FanOut, shard_id: int = 0,
                 ttl_s: float = DEFAULT_TTL_S) -> None:
        self.db = db
        self.membership = membership
        self.fanout = fanout
        self.shard_id = shard_id
        self.ttl_s = ttl_s
        # int-key federation state: shard-dictionary mirrors + remap
        # tables (cluster/dictsync.py) and the per-query scatter cache —
        # raw shard partials keyed by each shard's own change token, plus
        # the merged result for the all-tokens-unchanged fast path.
        self.dict_sync = DictSync()
        self._sql_cache: OrderedDict = OrderedDict()
        self._sql_cache_max = 64
        self.sql_cache_counters = {"warm_hits": 0, "shard_unchanged": 0,
                                   "shard_refetched": 0,
                                   "remap_failures": 0}
        # read-tier wiring (server/server.py, querier role): the
        # adopted-segment tier this coordinator serves sealed history
        # from, and the QueryCache the local partial routes through so
        # bucket slices (and the distributed partial cache) warm up.
        self.readtier = None
        self.query_cache = None

    # -- plumbing -----------------------------------------------------------

    def remote_peers(self) -> list[Peer]:
        # scatter targets are INGEST shards only: a querier replica
        # holds no rows of its own (its table is the same published
        # history this coordinator adopted) — scattering to one would
        # double-count every sealed row
        return self.membership.peers(include_self=False, ttl_s=self.ttl_s,
                                     role="ingest")

    def active(self) -> bool:
        """Any alive remote peer right now? (Single node: every query
        stays on the plain local path, zero overhead.)"""
        return bool(self.remote_peers())

    def ring(self) -> HashRing | None:
        return self.membership.ring

    def scatter(self, body: dict,
                hop_name: str) -> tuple[dict[int, object], list[int]]:
        return self.fanout.scatter(self.remote_peers(), body, hop_name)

    def _prune_clients(self) -> None:
        snap = self.membership.directory.snapshot()
        self.fanout.prune({p["addr"] for p in snap["peers"]})

    def scatter_claim(self, body: dict, hop_name: str):
        """Replica-exact scatter: -> (results, fed_info, local_db).

        Without a ring this is the PR-3 degraded path (raw local db,
        missing_shards annotated). With a ring, the op body carries the
        ring snapshot and the alive set; every shard — including this
        one, via the returned claim-view — reports each row exactly
        once: the row's first alive owner claims it. A failed shard
        triggers one re-scatter to the survivors with the shrunk alive
        set, because the survivors' first-round answers were computed
        assuming the dead shard would claim its own rows. The local
        partial MUST be computed from the returned db AFTER this call,
        so it sees the final alive set."""
        self._prune_clients()
        ring = self.ring()
        peers = self.remote_peers()
        if ring is None:
            results, missing = self.fanout.scatter(peers, body, hop_name)
            return results, self._info(results, missing), self.db
        alive = {self.shard_id} | {p.shard_id for p in peers}
        dead: set[int] = set()
        remaining = list(peers)
        results: dict[int, object] = {}
        failed: list[int] = []
        for _round in range(3):
            b = dict(body)
            b["ring"] = ring.snapshot()
            b["alive"] = sorted(alive)
            results, failed = self.fanout.scatter(remaining, b, hop_name)
            if not failed:
                break
            dead.update(failed)
            alive -= set(failed)
            remaining = [p for p in remaining
                         if p.shard_id not in set(failed)]
        exact = not failed and ring.covers(dead)
        info = {"shards": 1 + len(results) + len(dead),
                "missing_shards": [] if exact else sorted(dead),
                "ring_epoch": ring.epoch}
        if exact and dead:
            info["covered_shards"] = sorted(dead)
        local_db = ClaimDbView(self.db, ring, self.shard_id, alive)
        return results, info, local_db

    def _info(self, results: dict, missing: list[int]) -> dict:
        return {"shards": 1 + len(results) + len(missing),
                "missing_shards": missing}

    # -- DF-SQL -------------------------------------------------------------

    def sql_query(self, table, select: qsql.Select, sql_text: str,
                  org_id=None):
        """Entry point; with a read tier attached, adoption is frozen
        across the scatter AND the local partial so a manifest pointer
        swap mid-query cannot move segments between the shard's answer
        and the local read-tier scan (both sides see one consistent
        snapshot)."""
        if self.readtier is None:
            return self._sql_query(table, select, sql_text, org_id)
        with self.readtier.freeze():
            return self._sql_query(table, select, sql_text, org_id)

    def _sql_query(self, table, select: qsql.Select, sql_text: str,
                   org_id=None):
        """table/select: the coordinator's locally-resolved table and
        (org-scoped) AST. The exact resolved table NAME, the original
        sql_text and org_id travel to the shards, which re-scope
        themselves (the org filter lives in the AST, not the text) —
        both sides derive the partial layout from the same normalized
        text.

        Protocol v2 (int-key federation, per-column version-negotiated):
        the body carries ``"enc": 1`` plus per-shard ``if_state`` change
        tokens and ``dict_known`` mirror prefixes. A shard whose token
        matches replies {"kind": "unchanged"} and the coordinator reuses
        its cached raw partial; encoded replies are id-remapped into the
        coordinator's dictionaries before the vectorized merge. Shards
        running pre-encoding code ignore the new keys and return decoded
        partials, which join on the generic merge path unchanged."""
        import os
        cache_on = os.environ.get("DF_QUERY_CACHE", "1") != "0"
        ck = (table.name, " ".join(sql_text.split()), org_id)
        ent = self._sql_cache.get(ck) if cache_on else None
        peers = self.remote_peers()
        body = {"op": "sql_partial", "sql": sql_text,
                "table": table.name, "enc": 1,
                "dict_known": {
                    str(p.shard_id): self.dict_sync.known_state(
                        p.shard_id, table.name) for p in peers}}
        rt = self.readtier
        if rt is not None:
            # publish-gen handshake: tell each shard which of its
            # pointer generations we adopted. A shard whose current gen
            # matches answers WITHOUT its published segments (we serve
            # them from the read tier); any other shard answers in full
            # and we drop its adopted segments from the local scan.
            adopted = {str(p.shard_id): rt.gen_for(p.shard_id)
                       for p in peers if rt.gen_for(p.shard_id)}
            if adopted:
                body["readtier"] = adopted
        if org_id is not None:
            body["org_id"] = org_id
        if ent is not None:
            # one shared scatter body: per-shard tokens keyed by id
            body["if_state"] = {str(sid): st
                                for sid, st in ent["states"].items()
                                if st is not None}
        addr_by_sid = {p.shard_id: p.addr for p in peers}
        with qtrace.span("scatter", peers=len(peers)) as sc:
            results, info, db = self.scatter_claim(body,
                                                   hop_name="cluster.sql")
            sc.annotate(answered=len(results),
                        missing=len(info.get("missing_shards", [])))
        # integrity degradation: any shard serving with quarantined
        # (corrupt, awaiting-repair) segments says so on every reply —
        # including "unchanged" short-circuits, so a quarantine that
        # appears between two identical queries still surfaces. Same
        # honesty contract as missing_shards, different cause.
        deg_shards = {str(sid): r["degraded"]
                      for sid, r in results.items()
                      if isinstance(r, dict) and r.get("degraded")}
        if deg_shards:
            info = dict(info)
            info["degraded_shards"] = deg_shards
        local = db.table(table.name) if db is not self.db else table
        ring = self.ring()
        # the local partial's validity depends on the claim view too:
        # same table state under a different ring/alive set answers for
        # different rows
        ring_ctx = None if ring is None else [
            ring.epoch, ring.token,
            sorted(getattr(db, "_alive", []) or [])]

        parts_raw: dict[int, object] = {}
        states: dict[int, object] = {}
        unchanged: set[int] = set()
        failed_sync: list[int] = []
        for sid in sorted(results):
            r = results[sid]
            if isinstance(r, dict) and r.get("kind") == "unchanged":
                cached = (ent["parts"].get(sid)
                          if ent is not None else None)
                if cached is not None and \
                        ent["states"].get(sid) == r.get("state"):
                    parts_raw[sid] = cached
                    states[sid] = r.get("state")
                    unchanged.add(sid)
                    self.sql_cache_counters["shard_unchanged"] += 1
                    continue
                # shard honored a token we no longer hold the partial
                # for (evicted/raced) — fetch it fresh, no if_state
                r = self._shard_refetch(addr_by_sid.get(sid), body)
                if r is None:
                    failed_sync.append(sid)
                    continue
            states[sid] = (r.get("state")
                           if isinstance(r, dict) else None)
            parts_raw[sid] = r

        rt_excluded: set[int] = set()
        if rt is not None:
            def _acked(sid: int) -> bool:
                for src in (parts_raw.get(sid), results.get(sid)):
                    if isinstance(src, dict) and \
                            (src.get("rt") or {}).get("excluded"):
                        return True
                return False
            # an ANSWERING shard that did not apply the exclusion (gen
            # raced ahead, pre-readtier build, decoded fallback) covered
            # its published rows itself — drop its adopted segments from
            # the local scan or they would count twice. Dead shards stay
            # IN: the read tier is what keeps their history queryable.
            rt_excluded = {sid for sid in parts_raw
                           if rt.gen_for(sid) and not _acked(sid)}
            if rt_excluded:
                from deepflow_tpu.store.segcache import ShardExcludeView
                local = ShardExcludeView(local, frozenset(rt_excluded))
        # change_token, not sync_state: the remap below grows local
        # dictionaries, which must not read as "table changed". The
        # read-tier exclusion set joins the token: the same table state
        # answers for different rows under a different exclusion.
        local_token = [cache.change_token(table), ring_ctx] + \
            ([sorted(rt_excluded)] if rt is not None else [])

        if (ent is not None and not failed_sync
                and ent["local"] == local_token
                and set(parts_raw) == set(ent["parts"]) == unchanged
                and ent["missing"] == info["missing_shards"]):
            # nothing anywhere changed: skip remap + merge entirely
            self.sql_cache_counters["warm_hits"] += 1
            self._sql_cache.move_to_end(ck)
            info = dict(info)
            info["cache"] = "warm"
            info["shards_unchanged"] = len(unchanged)
            info["shards_refetched"] = 0
            return self._copy_result(ent["result"]), info

        if ent is not None and ent["local"] == local_token \
                and ent.get("local_part") is not None:
            local_part = ent["local_part"]
        elif rt is not None and self.query_cache is not None \
                and ring is None:
            # querier coordinator: the local read-tier partial goes
            # through the bucket cache so repeats recompute only stale
            # buckets — and cold buckets can come from a warm replica
            # via the distributed partial cache (QueryCache.dist)
            extra = (("rt", org_id) if not rt_excluded
                     else ("rt", org_id, tuple(sorted(rt_excluded))))
            local_part = self.query_cache.partial(
                local, sql_text, select=select, extra_key=extra)
        else:
            local_part = engine.execute_partial(local, select,
                                                encoded=True)
        # dictionary snapshot: remap + merge + decode all see the same
        # objects even if a local compaction swaps them mid-query
        local_dicts = dict(getattr(table, "dicts", {}) or {})

        def _decoder(key, _ld=local_dicts):
            d = _ld.get(key)
            if d is None:
                raise engine.QueryError(
                    f"unknown dictionary column {key!r} in partial")
            return d

        partials: list = [local_part]
        remap_sp = qtrace.span("dictsync.remap")
        remapped = 0
        for sid in sorted(parts_raw):
            raw = parts_raw[sid]
            if isinstance(raw, dict) and raw.get("dicts"):
                try:
                    partials.append(self.dict_sync.remap_partial(
                        sid, table.name, raw, local_dicts))
                    remapped += 1
                    continue
                except DictSyncError:
                    # mirror can't cover the shard's ids (malformed
                    # delta / gen race) — ask that shard for a decoded
                    # partial rather than dropping its rows
                    self.sql_cache_counters["remap_failures"] += 1
                    raw = self._shard_refetch(addr_by_sid.get(sid),
                                              body, decoded=True)
                    if raw is None:
                        failed_sync.append(sid)
                        del parts_raw[sid]
                        states.pop(sid, None)
                        continue
                    parts_raw[sid] = raw
                    states[sid] = (raw.get("state")
                                   if isinstance(raw, dict) else None)
            partials.append(raw)
        remap_sp.annotate(shards_remapped=remapped)
        remap_sp.finish()
        if failed_sync:
            info = dict(info)
            info["missing_shards"] = sorted(
                set(info["missing_shards"]) | set(failed_sync))
        with qtrace.span("merge", partials=len(partials)):
            res = engine.merge_partials(table, select, partials,
                                        decoder=_decoder)
        info = dict(info)
        info["cache"] = "cold"
        info["shards_unchanged"] = len(unchanged)
        info["shards_refetched"] = len(
            [sid for sid in parts_raw if sid not in unchanged])
        if cache_on:
            self._sql_cache[ck] = {
                "local": local_token, "local_part": local_part,
                "states": states, "parts": parts_raw,
                "missing": info["missing_shards"],
                "result": self._copy_result(res)}
            self._sql_cache.move_to_end(ck)
            while len(self._sql_cache) > self._sql_cache_max:
                self._sql_cache.popitem(last=False)
        return res, info

    @staticmethod
    def _copy_result(res: engine.QueryResult) -> engine.QueryResult:
        return engine.QueryResult(columns=list(res.columns),
                                  values=[list(r) for r in res.values])

    def _shard_refetch(self, addr, body: dict, *, decoded: bool = False):
        """One direct (non-scatter) retry against a single shard; None
        on failure. decoded=True downgrades to the pre-encoding wire
        form (the remap escape hatch)."""
        if not addr:
            return None
        b = dict(body)
        b.pop("if_state", None)
        if decoded:
            b.pop("enc", None)
            b.pop("dict_known", None)
        try:
            out = self.fanout.client(addr).call(b)
        except ShardCallError:
            return None
        self.sql_cache_counters["shard_refetched"] += 1
        return out

    # -- PromQL -------------------------------------------------------------

    def prom_db(self) -> _FederatedPromDb:
        return _FederatedPromDb(self)

    # -- Tempo / tracing ----------------------------------------------------

    def tempo_search(self, scan_fn, params: dict):
        """scan_fn(params, db): the local shard's scan
        (querier._tempo_scan), run against the claim-filtered view so
        the local partial is computed AFTER the scatter settles the
        alive set."""
        results, info, db = self.scatter_claim(
            {"op": "tempo_scan", "params": params},
            hop_name="cluster.tempo")
        parts = [scan_fn(params, db)]
        parts.extend(results[sid]["traces"] for sid in sorted(results))
        return merge_tempo_partials(parts), info

    def trace_spans(self, collect_fn, trace_id: str):
        """collect_fn(trace_id, db) -> span dicts; union across shards,
        build_trace_from_spans dedups by (span_id, start_ns, flow_id)
        at assembly."""
        results, info, db = self.scatter_claim(
            {"op": "trace_spans", "trace_id": trace_id},
            hop_name="cluster.trace")
        spans = list(collect_fn(trace_id, db))
        for sid in sorted(results):
            spans.extend(results[sid]["spans"])
        return spans, info

    # -- flame graphs -------------------------------------------------------

    def flame_stacks(self, flame_fn, params: dict):
        """flame_fn(params, db) -> (stacks, values); sum per-shard
        partials by stack string before one build_flame_tree at the
        coordinator."""
        results, info, db = self.scatter_claim(
            {"op": "profile_flame", "params": params},
            hop_name="cluster.flame")
        parts = [flame_fn(params, db)]
        for sid in sorted(results):
            r = results[sid]
            parts.append((r["stacks"], r["values"]))
        return merge_stack_values(parts), info

    # -- dfctl / status -----------------------------------------------------

    def local_table_counts(self) -> dict:
        return {name: len(self.db.table(name))
                for name in self.db.tables()}

    def cluster_status(self) -> dict:
        """Peer table for dfctl: every known peer with per-shard row
        counts and a timed status probe (sequential — a status page,
        not a query path)."""
        now_ns = time.time_ns()
        self.membership.refresh_self()
        snap = self.membership.directory.snapshot()
        rows = []
        for p in [Peer.from_dict(d) for d in snap["peers"]]:
            # "raw_rows", not "rows": with replication each HIGH/MID row
            # physically exists on R shards, so per-shard counts (and
            # their sum) overstate the logical row count by ~R× — the
            # label says what is actually being counted
            entry = {"shard_id": p.shard_id, "addr": p.addr,
                     "epoch": p.epoch,
                     "last_seen_s": round(
                         max(0, now_ns - p.last_seen_ns) / 1e9, 1),
                     "alive": True, "latency_ms": None, "raw_rows": None}
            if p.shard_id == self.shard_id:
                t0 = time.monotonic()
                counts = self.local_table_counts()
                entry["latency_ms"] = round(
                    (time.monotonic() - t0) * 1e3, 2)
                entry["raw_rows"] = sum(counts.values())
            else:
                try:
                    t0 = time.monotonic()
                    counts = self.fanout.client(p.addr).call(
                        {"op": "table_counts"})
                    entry["latency_ms"] = round(
                        (time.monotonic() - t0) * 1e3, 2)
                    entry["raw_rows"] = sum(counts.values())
                except ShardCallError as e:
                    entry["alive"] = False
                    entry["error"] = str(e)
            rows.append(entry)
        out = {"shard_id": self.shard_id,
               "version": self.membership.directory.version,
               "peers": rows,
               "fanout": self.fanout.stats()}
        ring = self.ring()
        if ring is not None:
            # NOTE: per-shard "raw_rows" above are RAW counts — with
            # replication each HIGH/MID row exists on R shards, so the
            # sum over peers overstates the logical row count by ~R×.
            out["ring"] = {"epoch": ring.epoch, "token": ring.token,
                           "replication": ring.replication,
                           "members": sorted(ring.members)}
        return out
