"""Scatter-gather coordinator: one querier answers for N sharded servers.

Merge semantics (docs/CLUSTER.md spells out the contract):

- DF-SQL: partial-aggregate push-down. Each shard runs
  engine.execute_partial (groups keyed by DECODED values — SmartEncoding
  ids are shard-local and never merged); the coordinator reduces with
  engine.merge_partials. Exact for SUM/COUNT/MIN/MAX/AVG/LAST/
  COUNT(DISTINCT); PERCENTILE merges histogram sketches (~2% error).
- PromQL: Thanos-style raw-selector fan-out. Only fetch_raw is
  federated (via the db-shim below); the whole AST evaluates at the
  coordinator, so every PromQL function stays EXACT.
- Tempo search: shards return per-trace scan partials; one trace's spans
  may land on many shards, so trace-level start/end/duration exist only
  after the merge — duration filters and the limit apply here, never
  shard-side.
- Trace assembly / flame graphs: span-dict union (dedup by
  (span_id, start_ns, flow_id) in build_trace_from_spans) and
  stack-string sums.
- Degraded mode: a dead or timed-out shard never fails the query; its
  ids land in the "missing_shards" annotation of the partial result.
"""

from __future__ import annotations

import time

import numpy as np

from deepflow_tpu.cluster.membership import (DEFAULT_TTL_S,
                                             ClusterMembership, Peer)
from deepflow_tpu.cluster.remote import FanOut, ShardCallError
from deepflow_tpu.query import engine, promql
from deepflow_tpu.query import sql as qsql
from deepflow_tpu.query.flamegraph import merge_stack_values


def merge_tempo_partials(parts: list[list[dict]]) -> list[dict]:
    """Union per-shard Tempo scan partials by traceID.

    Per trace: start = min, end = max (span sets are disjoint-ish across
    shards), _matched OR (a tag may match on any shard's spans), root
    fields from whichever shard saw the earliest span (_root_t)."""
    by_id: dict[str, dict] = {}
    for part in parts:
        for tr in part:
            cur = by_id.get(tr["traceID"])
            if cur is None:
                by_id[tr["traceID"]] = dict(tr)
                continue
            if tr.get("_root_t", 0) < cur.get("_root_t", 0):
                cur["rootServiceName"] = tr.get("rootServiceName", "")
                cur["rootTraceName"] = tr.get("rootTraceName", "")
                cur["_root_t"] = tr.get("_root_t", 0)
            cur["_start_ns"] = min(cur["_start_ns"], tr["_start_ns"])
            cur["_end_ns"] = max(cur["_end_ns"], tr["_end_ns"])
            cur["spanCount"] = cur.get("spanCount", 0) + tr.get(
                "spanCount", 0)
            cur["_matched"] = cur.get("_matched", False) or tr.get(
                "_matched", False)
    return list(by_id.values())


class _FederatedPromDb:
    """Database shim handed to promql.evaluate: intercepts fetch_raw
    (the promql_fetch_raw hook) and merges local + remote RawSeries by
    full label set. Everything else (table/tables for metadata paths)
    delegates to the local store. One instance per request — it
    accumulates that request's missing_shards."""

    def __init__(self, coord: "FederationCoordinator") -> None:
        self._coord = coord
        self._db = coord.db
        self.missing_shards: set[int] = set()

    def table(self, name: str):
        return self._db.table(name)

    def tables(self) -> list[str]:
        return self._db.tables()

    def __getattr__(self, name: str):
        return getattr(self._db, name)

    def promql_fetch_raw(self, vs, lo_s: float, hi_s: float):
        local_unknown = False
        try:
            local = promql.fetch_raw(self._db, vs, lo_s, hi_s)
        except promql.UnknownMetricError:
            local, local_unknown = [], True
        results, missing = self._coord.scatter(
            {"op": "promql_raw", "metric": vs.metric,
             "matchers": [list(m) for m in vs.matchers],
             "lo_s": float(lo_s), "hi_s": float(hi_s)},
            hop_name="cluster.promql")
        self.missing_shards.update(missing)
        remote_known = False
        merged: dict[tuple, promql.RawSeries] = {}

        def fold(series_list):
            for s in series_list:
                key = tuple(sorted((k, str(v))
                            for k, v in s.labels.items()))
                cur = merged.get(key)
                if cur is None:
                    merged[key] = s
                else:
                    t = np.concatenate([cur.t, s.t])
                    v = np.concatenate([cur.v, s.v])
                    order = np.argsort(t, kind="stable")
                    cur.t, cur.v = t[order], v[order]

        fold(local)
        for res in results.values():
            if res.get("unknown"):
                continue
            remote_known = True
            fold([promql.RawSeries(
                labels=d["labels"],
                t=np.asarray(d["t"], dtype=np.int64),
                v=np.asarray(d["v"], dtype=np.float64),
                counter=bool(d["counter"])) for d in res["series"]])
        if local_unknown and not remote_known and not self.missing_shards:
            # only a clean miss is an error: with a shard unreachable the
            # metric may live exactly there, and the degraded contract
            # says partial-and-annotated, never a 500
            raise promql.UnknownMetricError(
                f"unknown metric {vs.metric!r} on every shard")
        return list(merged.values())


class FederationCoordinator:
    """Ties membership + FanOut + the per-signal merge steps together.
    Every public method returns (result, fed_info) where fed_info is
    {"shards": total answering, "missing_shards": [ids]} — the degraded
    -mode contract: partial data is annotated, never a 500."""

    def __init__(self, db, membership: ClusterMembership,
                 fanout: FanOut, shard_id: int = 0,
                 ttl_s: float = DEFAULT_TTL_S) -> None:
        self.db = db
        self.membership = membership
        self.fanout = fanout
        self.shard_id = shard_id
        self.ttl_s = ttl_s

    # -- plumbing -----------------------------------------------------------

    def remote_peers(self) -> list[Peer]:
        return self.membership.peers(include_self=False, ttl_s=self.ttl_s)

    def active(self) -> bool:
        """Any alive remote peer right now? (Single node: every query
        stays on the plain local path, zero overhead.)"""
        return bool(self.remote_peers())

    def scatter(self, body: dict,
                hop_name: str) -> tuple[dict[int, object], list[int]]:
        return self.fanout.scatter(self.remote_peers(), body, hop_name)

    def _info(self, results: dict, missing: list[int]) -> dict:
        return {"shards": 1 + len(results) + len(missing),
                "missing_shards": missing}

    # -- DF-SQL -------------------------------------------------------------

    def sql_query(self, table, select: qsql.Select, sql_text: str,
                  org_id=None):
        """table/select: the coordinator's locally-resolved table and
        (org-scoped) AST. The exact resolved table NAME, the original
        sql_text and org_id travel to the shards, which re-scope
        themselves (the org filter lives in the AST, not the text) —
        both sides derive the partial layout from the same normalized
        text."""
        body = {"op": "sql_partial", "sql": sql_text,
                "table": table.name}
        if org_id is not None:
            body["org_id"] = org_id
        results, missing = self.scatter(body, hop_name="cluster.sql")
        partials = [engine.execute_partial(table, select)]
        partials.extend(results[sid] for sid in sorted(results))
        res = engine.merge_partials(table, select, partials)
        return res, self._info(results, missing)

    # -- PromQL -------------------------------------------------------------

    def prom_db(self) -> _FederatedPromDb:
        return _FederatedPromDb(self)

    # -- Tempo / tracing ----------------------------------------------------

    def tempo_search(self, scan_fn, params: dict):
        """scan_fn: the local shard's scan (querier._tempo_scan)."""
        results, missing = self.scatter(
            {"op": "tempo_scan", "params": params},
            hop_name="cluster.tempo")
        parts = [scan_fn(params)]
        parts.extend(results[sid]["traces"] for sid in sorted(results))
        return merge_tempo_partials(parts), self._info(results, missing)

    def trace_spans(self, local_spans: list[dict], trace_id: str):
        """Union span dicts across shards; build_trace_from_spans dedups
        by (span_id, start_ns, flow_id) at assembly."""
        results, missing = self.scatter(
            {"op": "trace_spans", "trace_id": trace_id},
            hop_name="cluster.trace")
        spans = list(local_spans)
        for sid in sorted(results):
            spans.extend(results[sid]["spans"])
        return spans, self._info(results, missing)

    # -- flame graphs -------------------------------------------------------

    def flame_stacks(self, local_part: tuple[list, list], params: dict):
        """Sum per-shard (stacks, values) by stack string before one
        build_flame_tree at the coordinator."""
        results, missing = self.scatter(
            {"op": "profile_flame", "params": params},
            hop_name="cluster.flame")
        parts = [local_part]
        for sid in sorted(results):
            r = results[sid]
            parts.append((r["stacks"], r["values"]))
        return merge_stack_values(parts), self._info(results, missing)

    # -- dfctl / status -----------------------------------------------------

    def local_table_counts(self) -> dict:
        return {name: len(self.db.table(name))
                for name in self.db.tables()}

    def cluster_status(self) -> dict:
        """Peer table for dfctl: every known peer with per-shard row
        counts and a timed status probe (sequential — a status page,
        not a query path)."""
        now_ns = time.time_ns()
        self.membership.refresh_self()
        snap = self.membership.directory.snapshot()
        rows = []
        for p in [Peer.from_dict(d) for d in snap["peers"]]:
            entry = {"shard_id": p.shard_id, "addr": p.addr,
                     "epoch": p.epoch,
                     "last_seen_s": round(
                         max(0, now_ns - p.last_seen_ns) / 1e9, 1),
                     "alive": True, "latency_ms": None, "rows": None}
            if p.shard_id == self.shard_id:
                t0 = time.monotonic()
                counts = self.local_table_counts()
                entry["latency_ms"] = round(
                    (time.monotonic() - t0) * 1e3, 2)
                entry["rows"] = sum(counts.values())
            else:
                try:
                    t0 = time.monotonic()
                    counts = self.fanout.client(p.addr).call(
                        {"op": "table_counts"})
                    entry["latency_ms"] = round(
                        (time.monotonic() - t0) * 1e3, 2)
                    entry["rows"] = sum(counts.values())
                except ShardCallError as e:
                    entry["alive"] = False
                    entry["error"] = str(e)
            rows.append(entry)
        return {"shard_id": self.shard_id,
                "version": self.membership.directory.version,
                "peers": rows,
                "fanout": self.fanout.stats()}
