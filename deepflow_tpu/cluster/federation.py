"""Scatter-gather coordinator: one querier answers for N sharded servers.

Merge semantics (docs/CLUSTER.md spells out the contract):

- DF-SQL: partial-aggregate push-down. Each shard runs
  engine.execute_partial (groups keyed by DECODED values — SmartEncoding
  ids are shard-local and never merged); the coordinator reduces with
  engine.merge_partials. Exact for SUM/COUNT/MIN/MAX/AVG/LAST/
  COUNT(DISTINCT); PERCENTILE merges histogram sketches (~2% error).
- PromQL: Thanos-style raw-selector fan-out. Only fetch_raw is
  federated (via the db-shim below); the whole AST evaluates at the
  coordinator, so every PromQL function stays EXACT.
- Tempo search: shards return per-trace scan partials; one trace's spans
  may land on many shards, so trace-level start/end/duration exist only
  after the merge — duration filters and the limit apply here, never
  shard-side.
- Trace assembly / flame graphs: span-dict union (dedup by
  (span_id, start_ns, flow_id) in build_trace_from_spans) and
  stack-string sums.
- Degraded mode: a dead or timed-out shard never fails the query; its
  ids land in the "missing_shards" annotation of the partial result.
- Replicated mode (a HashRing is active): every scatter ships the ring
  snapshot + the alive set, each shard answers from its claim-filtered
  view (exactly one alive owner reports each row), and a shard failure
  triggers ONE re-scatter with the shrunk alive set so a dead primary's
  rows get promoted to the surviving replica. When every dead shard is
  covered (dead ⊆ ring members, |dead| ≤ R−1) the result is EXACT:
  missing_shards stays empty and the dead ids land in covered_shards.
"""

from __future__ import annotations

import time

import numpy as np

from deepflow_tpu.cluster.hashring import ClaimDbView, HashRing
from deepflow_tpu.cluster.membership import (DEFAULT_TTL_S,
                                             ClusterMembership, Peer)
from deepflow_tpu.cluster.remote import FanOut, ShardCallError
from deepflow_tpu.query import engine, promql
from deepflow_tpu.query import sql as qsql
from deepflow_tpu.query.flamegraph import merge_stack_values


def merge_tempo_partials(parts: list[list[dict]]) -> list[dict]:
    """Union per-shard Tempo scan partials by traceID.

    Per trace: start = min, end = max (span sets are disjoint-ish across
    shards), _matched OR (a tag may match on any shard's spans), root
    fields from whichever shard saw the earliest span (_root_t)."""
    by_id: dict[str, dict] = {}
    for part in parts:
        for tr in part:
            cur = by_id.get(tr["traceID"])
            if cur is None:
                by_id[tr["traceID"]] = dict(tr)
                continue
            if tr.get("_root_t", 0) < cur.get("_root_t", 0):
                cur["rootServiceName"] = tr.get("rootServiceName", "")
                cur["rootTraceName"] = tr.get("rootTraceName", "")
                cur["_root_t"] = tr.get("_root_t", 0)
            cur["_start_ns"] = min(cur["_start_ns"], tr["_start_ns"])
            cur["_end_ns"] = max(cur["_end_ns"], tr["_end_ns"])
            cur["spanCount"] = cur.get("spanCount", 0) + tr.get(
                "spanCount", 0)
            cur["_matched"] = cur.get("_matched", False) or tr.get(
                "_matched", False)
    return list(by_id.values())


class _FederatedPromDb:
    """Database shim handed to promql.evaluate: intercepts fetch_raw
    (the promql_fetch_raw hook) and merges local + remote RawSeries by
    full label set. Everything else (table/tables for metadata paths)
    delegates to the local store. One instance per request — it
    accumulates that request's missing_shards."""

    def __init__(self, coord: "FederationCoordinator") -> None:
        self._coord = coord
        self._db = coord.db
        self.missing_shards: set[int] = set()
        self.fed_info: dict = {}

    def table(self, name: str):
        return self._db.table(name)

    def tables(self) -> list[str]:
        return self._db.tables()

    def __getattr__(self, name: str):
        return getattr(self._db, name)

    def promql_fetch_raw(self, vs, lo_s: float, hi_s: float):
        results, info, db = self._coord.scatter_claim(
            {"op": "promql_raw", "metric": vs.metric,
             "matchers": [list(m) for m in vs.matchers],
             "lo_s": float(lo_s), "hi_s": float(hi_s)},
            hop_name="cluster.promql")
        self.missing_shards.update(info["missing_shards"])
        self.fed_info = info
        local_unknown = False
        try:
            local = promql.fetch_raw(db, vs, lo_s, hi_s)
        except promql.UnknownMetricError:
            local, local_unknown = [], True
        remote_known = False
        merged: dict[tuple, promql.RawSeries] = {}

        def fold(series_list):
            for s in series_list:
                key = tuple(sorted((k, str(v))
                            for k, v in s.labels.items()))
                cur = merged.get(key)
                if cur is None:
                    merged[key] = s
                else:
                    t = np.concatenate([cur.t, s.t])
                    v = np.concatenate([cur.v, s.v])
                    order = np.argsort(t, kind="stable")
                    cur.t, cur.v = t[order], v[order]

        fold(local)
        for res in results.values():
            if res.get("unknown"):
                continue
            remote_known = True
            fold([promql.RawSeries(
                labels=d["labels"],
                t=np.asarray(d["t"], dtype=np.int64),
                v=np.asarray(d["v"], dtype=np.float64),
                counter=bool(d["counter"])) for d in res["series"]])
        if local_unknown and not remote_known and not self.missing_shards:
            # only a clean miss is an error: with a shard unreachable the
            # metric may live exactly there, and the degraded contract
            # says partial-and-annotated, never a 500
            raise promql.UnknownMetricError(
                f"unknown metric {vs.metric!r} on every shard")
        return list(merged.values())


class FederationCoordinator:
    """Ties membership + FanOut + the per-signal merge steps together.
    Every public method returns (result, fed_info) where fed_info is
    {"shards": total answering, "missing_shards": [ids]} — the degraded
    -mode contract: partial data is annotated, never a 500."""

    def __init__(self, db, membership: ClusterMembership,
                 fanout: FanOut, shard_id: int = 0,
                 ttl_s: float = DEFAULT_TTL_S) -> None:
        self.db = db
        self.membership = membership
        self.fanout = fanout
        self.shard_id = shard_id
        self.ttl_s = ttl_s

    # -- plumbing -----------------------------------------------------------

    def remote_peers(self) -> list[Peer]:
        return self.membership.peers(include_self=False, ttl_s=self.ttl_s)

    def active(self) -> bool:
        """Any alive remote peer right now? (Single node: every query
        stays on the plain local path, zero overhead.)"""
        return bool(self.remote_peers())

    def ring(self) -> HashRing | None:
        return self.membership.ring

    def scatter(self, body: dict,
                hop_name: str) -> tuple[dict[int, object], list[int]]:
        return self.fanout.scatter(self.remote_peers(), body, hop_name)

    def _prune_clients(self) -> None:
        snap = self.membership.directory.snapshot()
        self.fanout.prune({p["addr"] for p in snap["peers"]})

    def scatter_claim(self, body: dict, hop_name: str):
        """Replica-exact scatter: -> (results, fed_info, local_db).

        Without a ring this is the PR-3 degraded path (raw local db,
        missing_shards annotated). With a ring, the op body carries the
        ring snapshot and the alive set; every shard — including this
        one, via the returned claim-view — reports each row exactly
        once: the row's first alive owner claims it. A failed shard
        triggers one re-scatter to the survivors with the shrunk alive
        set, because the survivors' first-round answers were computed
        assuming the dead shard would claim its own rows. The local
        partial MUST be computed from the returned db AFTER this call,
        so it sees the final alive set."""
        self._prune_clients()
        ring = self.ring()
        peers = self.remote_peers()
        if ring is None:
            results, missing = self.fanout.scatter(peers, body, hop_name)
            return results, self._info(results, missing), self.db
        alive = {self.shard_id} | {p.shard_id for p in peers}
        dead: set[int] = set()
        remaining = list(peers)
        results: dict[int, object] = {}
        failed: list[int] = []
        for _round in range(3):
            b = dict(body)
            b["ring"] = ring.snapshot()
            b["alive"] = sorted(alive)
            results, failed = self.fanout.scatter(remaining, b, hop_name)
            if not failed:
                break
            dead.update(failed)
            alive -= set(failed)
            remaining = [p for p in remaining
                         if p.shard_id not in set(failed)]
        exact = not failed and ring.covers(dead)
        info = {"shards": 1 + len(results) + len(dead),
                "missing_shards": [] if exact else sorted(dead),
                "ring_epoch": ring.epoch}
        if exact and dead:
            info["covered_shards"] = sorted(dead)
        local_db = ClaimDbView(self.db, ring, self.shard_id, alive)
        return results, info, local_db

    def _info(self, results: dict, missing: list[int]) -> dict:
        return {"shards": 1 + len(results) + len(missing),
                "missing_shards": missing}

    # -- DF-SQL -------------------------------------------------------------

    def sql_query(self, table, select: qsql.Select, sql_text: str,
                  org_id=None):
        """table/select: the coordinator's locally-resolved table and
        (org-scoped) AST. The exact resolved table NAME, the original
        sql_text and org_id travel to the shards, which re-scope
        themselves (the org filter lives in the AST, not the text) —
        both sides derive the partial layout from the same normalized
        text."""
        body = {"op": "sql_partial", "sql": sql_text,
                "table": table.name}
        if org_id is not None:
            body["org_id"] = org_id
        results, info, db = self.scatter_claim(body, hop_name="cluster.sql")
        local = db.table(table.name) if db is not self.db else table
        partials = [engine.execute_partial(local, select)]
        partials.extend(results[sid] for sid in sorted(results))
        res = engine.merge_partials(table, select, partials)
        return res, info

    # -- PromQL -------------------------------------------------------------

    def prom_db(self) -> _FederatedPromDb:
        return _FederatedPromDb(self)

    # -- Tempo / tracing ----------------------------------------------------

    def tempo_search(self, scan_fn, params: dict):
        """scan_fn(params, db): the local shard's scan
        (querier._tempo_scan), run against the claim-filtered view so
        the local partial is computed AFTER the scatter settles the
        alive set."""
        results, info, db = self.scatter_claim(
            {"op": "tempo_scan", "params": params},
            hop_name="cluster.tempo")
        parts = [scan_fn(params, db)]
        parts.extend(results[sid]["traces"] for sid in sorted(results))
        return merge_tempo_partials(parts), info

    def trace_spans(self, collect_fn, trace_id: str):
        """collect_fn(trace_id, db) -> span dicts; union across shards,
        build_trace_from_spans dedups by (span_id, start_ns, flow_id)
        at assembly."""
        results, info, db = self.scatter_claim(
            {"op": "trace_spans", "trace_id": trace_id},
            hop_name="cluster.trace")
        spans = list(collect_fn(trace_id, db))
        for sid in sorted(results):
            spans.extend(results[sid]["spans"])
        return spans, info

    # -- flame graphs -------------------------------------------------------

    def flame_stacks(self, flame_fn, params: dict):
        """flame_fn(params, db) -> (stacks, values); sum per-shard
        partials by stack string before one build_flame_tree at the
        coordinator."""
        results, info, db = self.scatter_claim(
            {"op": "profile_flame", "params": params},
            hop_name="cluster.flame")
        parts = [flame_fn(params, db)]
        for sid in sorted(results):
            r = results[sid]
            parts.append((r["stacks"], r["values"]))
        return merge_stack_values(parts), info

    # -- dfctl / status -----------------------------------------------------

    def local_table_counts(self) -> dict:
        return {name: len(self.db.table(name))
                for name in self.db.tables()}

    def cluster_status(self) -> dict:
        """Peer table for dfctl: every known peer with per-shard row
        counts and a timed status probe (sequential — a status page,
        not a query path)."""
        now_ns = time.time_ns()
        self.membership.refresh_self()
        snap = self.membership.directory.snapshot()
        rows = []
        for p in [Peer.from_dict(d) for d in snap["peers"]]:
            entry = {"shard_id": p.shard_id, "addr": p.addr,
                     "epoch": p.epoch,
                     "last_seen_s": round(
                         max(0, now_ns - p.last_seen_ns) / 1e9, 1),
                     "alive": True, "latency_ms": None, "rows": None}
            if p.shard_id == self.shard_id:
                t0 = time.monotonic()
                counts = self.local_table_counts()
                entry["latency_ms"] = round(
                    (time.monotonic() - t0) * 1e3, 2)
                entry["rows"] = sum(counts.values())
            else:
                try:
                    t0 = time.monotonic()
                    counts = self.fanout.client(p.addr).call(
                        {"op": "table_counts"})
                    entry["latency_ms"] = round(
                        (time.monotonic() - t0) * 1e3, 2)
                    entry["rows"] = sum(counts.values())
                except ShardCallError as e:
                    entry["alive"] = False
                    entry["error"] = str(e)
            rows.append(entry)
        out = {"shard_id": self.shard_id,
               "version": self.membership.directory.version,
               "peers": rows,
               "fanout": self.fanout.stats()}
        ring = self.ring()
        if ring is not None:
            # NOTE: per-shard "rows" above are RAW counts — with
            # replication each HIGH/MID row exists on R shards, so the
            # sum over peers overstates the logical row count by ~R×.
            out["ring"] = {"epoch": ring.epoch, "token": ring.token,
                           "replication": ring.replication,
                           "members": sorted(ring.members)}
        return out
