"""Mergeable histogram sketch for federated percentiles.

Exact percentiles do not decompose over shards, so PERCENTILE is the
one aggregate the cluster merges approximately (documented in
docs/CLUSTER.md). The sketch is a log-scaled bucket histogram in the
DDSketch family: relative error is bounded by the bucket growth factor
(gamma), merge is bucket-wise addition, and the wire form is a sparse
{bucket_index: count} dict plus exact min/max so tail quantiles clamp
to observed bounds.
"""

from __future__ import annotations

import math

import numpy as np

_GAMMA = 1.02            # ~2% relative error per bucket
_LOG_GAMMA = math.log(_GAMMA)


class HistogramSketch:
    def __init__(self) -> None:
        self.buckets: dict[int, int] = {}   # log-bucket -> count
        self.zeros = 0                      # values <= 0 (durations: zero)
        self.count = 0
        self.min = math.inf
        self.max = -math.inf

    def add_many(self, values: np.ndarray) -> None:
        a = np.asarray(values, dtype=np.float64)
        if not len(a):
            return
        self.count += int(len(a))
        self.min = min(self.min, float(a.min()))
        self.max = max(self.max, float(a.max()))
        pos = a[a > 0]
        self.zeros += int(len(a) - len(pos))
        if len(pos):
            idx = np.ceil(np.log(pos) / _LOG_GAMMA).astype(np.int64)
            for b, c in zip(*np.unique(idx, return_counts=True)):
                b = int(b)
                self.buckets[b] = self.buckets.get(b, 0) + int(c)

    def merge(self, other: "HistogramSketch") -> "HistogramSketch":
        for b, c in other.buckets.items():
            self.buckets[b] = self.buckets.get(b, 0) + c
        self.zeros += other.zeros
        self.count += other.count
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        return self

    def percentile(self, p: float) -> float:
        if self.count == 0:
            return 0.0
        # nearest-rank over (zeros, then ascending log buckets); each hit
        # reports the bucket's geometric midpoint, clamped to true min/max
        rank = max(1, math.ceil(p / 100.0 * self.count))
        if rank <= self.zeros:
            return max(0.0, self.min)
        seen = self.zeros
        for b in sorted(self.buckets):
            seen += self.buckets[b]
            if seen >= rank:
                mid = 2.0 * (_GAMMA ** b) / (1.0 + _GAMMA)
                return float(min(max(mid, self.min), self.max))
        return float(self.max)

    def to_dict(self) -> dict:
        return {"b": {str(k): v for k, v in self.buckets.items()},
                "z": self.zeros, "n": self.count,
                "lo": (None if self.count == 0 else self.min),
                "hi": (None if self.count == 0 else self.max)}

    @classmethod
    def from_dict(cls, d: dict) -> "HistogramSketch":
        s = cls()
        s.buckets = {int(k): int(v) for k, v in (d.get("b") or {}).items()}
        s.zeros = int(d.get("z", 0))
        s.count = int(d.get("n", 0))
        s.min = math.inf if d.get("lo") is None else float(d["lo"])
        s.max = -math.inf if d.get("hi") is None else float(d["hi"])
        return s
