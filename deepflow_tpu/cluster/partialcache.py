"""Cluster-wide partial-aggregate cache: compute each bucket once.

N querier replicas serving the same dashboard all slice the same
aggregate query into the same 60s bucket grid (query/cache.py). Without
coordination each replica scans every bucket cold once — N× the work
for byte-identical slices. This module makes the per-bucket ENCODED
partials shareable across replicas:

- **adverts**: each node folds digests of its warm shareable bucket
  stores — sha1(table | normalized SQL | org) — into the membership
  join exchange (cluster/membership.py gossips them both directions),
  so every replica knows who is warm after one heartbeat round-trip.
- **fetch**: on a local bucket miss with a live advert, the replica
  POSTs /v1/cache/partial to the warm peer and receives the matching
  slices in one CACHE_PARTIAL frame (cluster/wire.py — the jsonb form,
  uint32 id columns travel as raw blobs).
- **validity**: bucket write marks are node-local counters and mean
  nothing across nodes. What makes a peer's slice valid here is that
  both tables hold EXACTLY the same rows: both are pure read-tier views
  (no local stripe rows) whose adopted publish state hashes to the same
  ``pub_token`` (store/segcache.py ReadTier._retoken — a content hash
  over per-shard fn sets + dict states, identical across replicas at
  the same adopted state). The server additionally validates each slice
  against its OWN current marks/gens, so a slice is served only while
  it is live there too.
- **id spaces**: slice partials carry the serving node's local
  dictionary ids. The response ships one dict_sync delta (the same
  build_sync the federation uses) and the requester remaps ids through
  its federation DictSync mirror of that peer, then re-labels the slice
  with its OWN dictionary states — after which the slice is
  indistinguishable from a locally-scanned one and folds through
  engine.combine_partials with the local slices.

The ledger proves the cluster-wide compute-once claim: across a quiesced
query storm, sum(bucket_misses) over replicas counts each (query,
bucket) scan once, and served_buckets on warm nodes equals
fetched_buckets on cold ones (cli/readtier_check.py asserts both).
"""

from __future__ import annotations

import hashlib
import json
import logging
import threading
import urllib.request

from deepflow_tpu.cluster import wire
from deepflow_tpu.cluster.dictsync import DictSyncError, build_sync
from deepflow_tpu.query import qtrace
from deepflow_tpu.query.cache import normalize_sql

log = logging.getLogger("df.partialcache")

# extra_key variants that are NOT org-equivalent (ring claim views,
# ad-hoc rewrites) must never be shared: marker for "not shareable"
_UNSHARED = object()


def share_org(extra_key):
    """The org a bucket-cache key variant answers for, iff the variant
    is shareable across replicas — i.e. the extra_key encodes nothing
    beyond org scoping. Ring claim contexts (("fed", org, ring_repr)
    with an active ring) and read-tier exclusion sets (("rt", org,
    excluded)) answer for different row subsets and return _UNSHARED."""
    if extra_key is None:
        return None
    if isinstance(extra_key, tuple) and len(extra_key) == 2 \
            and extra_key[0] in ("org", "rt"):
        return extra_key[1]
    return _UNSHARED


def key_variants(org) -> list:
    """Every extra_key form under which org-equivalent buckets may be
    cached locally (the serve-side lookup candidates): the coordinator
    read-tier form and the plain local-query form. Shard-side ("fed",
    ...) variants never exist on a pure read-tier node — queriers are
    not scattered to."""
    return [("rt", org), None if org is None else ("org", org)]


def digest_of(table: str, sql: str, org) -> str:
    return hashlib.sha1(
        f"{table}|{normalize_sql(sql)}|{org!r}".encode()).hexdigest()[:16]


class PartialCache:
    """One node's half of the distributed partial-aggregate cache:
    requester (QueryCache.dist hook) + server (/v1/cache/partial) +
    advert source (membership.cache_adv_local hook)."""

    def __init__(self, query_cache, membership, dict_sync, db,
                 shard_id: int = 0, telemetry=None,
                 api_token: str | None = None,
                 timeout_s: float = 2.0) -> None:
        self.query_cache = query_cache
        self.membership = membership
        # the FEDERATION DictSync: peer partials arrive in the peer's
        # local id space, exactly like shard partials do — the mirrors
        # are keyed by the peer's shard_id either way
        self.dict_sync = dict_sync
        self.db = db
        self.readtier = None          # set by server wiring on queriers
        self.shard_id = shard_id
        self.api_token = api_token
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self.counters = {"advertised": 0, "fetches": 0,
                         "fetched_buckets": 0, "fetch_errors": 0,
                         "remap_failures": 0, "served": 0,
                         "served_buckets": 0, "serve_rejects": 0}
        self._hop = (telemetry.hop("cluster.partialcache")
                     if telemetry is not None else None)
        # install both hooks — the cache calls dist() on bucket misses,
        # membership gossips advertised_digests() on every heartbeat
        query_cache.dist = self.fetch_buckets
        membership.cache_adv_local = self.advertised_digests

    # -- advert side ---------------------------------------------------------

    def advertised_digests(self) -> list[str]:
        if self.readtier is None:
            return []
        out: set[str] = set()
        for tname, sql, extra in self.query_cache.warm_keys():
            org = share_org(extra)
            if org is _UNSHARED:
                continue
            if not self.readtier.pub_token(tname):
                # pub_token returns "" (not None) before any adoption:
                # nothing shareable to advertise for this table yet
                continue
            out.add(digest_of(tname, sql, org))
        with self._lock:
            self.counters["advertised"] = len(out)
        return sorted(out)

    # -- requester side ------------------------------------------------------

    def _pure(self, table) -> bool:
        """Shareable content = every row comes from the adopted remote
        tier. A table with ANY local rows (querier selfstats, an ingest
        node's stripes) diverges per node and must not share."""
        tier = getattr(table, "tier", None)
        return tier is not None and len(table) == tier.rows

    def fetch_buckets(self, table, key: tuple, buckets: list,
                      gens) -> dict:
        """QueryCache.dist hook: -> {bucket: partial} in LOCAL id space
        for whatever slices a warm advertised peer can serve."""
        tname, sql, extra = key
        org = share_org(extra)
        if org is _UNSHARED or not buckets or self.readtier is None:
            return {}
        tok = self.readtier.pub_token(tname)
        if not tok or not self._pure(table):
            # "" = no adopted state: a fetch would ship an empty token
            # the server side always rejects — skip the round-trip
            return {}
        adv = self.membership.advert_for(digest_of(tname, sql, org))
        if adv is None:
            return {}
        sid, addr = int(adv[0]), str(adv[1])
        body = {"table": tname, "sql": sql, "org": org,
                "pub_token": tok,
                "buckets": sorted(int(b) for b in buckets),
                "dict_known": self.dict_sync.known_state(sid, tname)}
        with self._lock:
            self.counters["fetches"] += 1
        fetch_sp = qtrace.span("partialcache.fetch", peer=sid, addr=addr,
                               buckets=len(buckets))
        try:
            resp, _rsid = self._call(addr, body)
        except Exception as e:
            with self._lock:
                self.counters["fetch_errors"] += 1
            if self._hop is not None:
                self._hop.account(emitted=1, dropped=1, reason="error")
            fetch_sp.annotate(outcome="error")
            fetch_sp.finish()
            log.debug("partialcache fetch from %s failed: %s", addr, e)
            return {}
        got = (resp or {}).get("buckets") or {}
        for col, sync in ((resp or {}).get("dict_sync") or {}).items():
            self.dict_sync.apply_sync(sid, tname, col, sync)
        local_dicts = dict(getattr(table, "dicts", {}) or {})
        out: dict[int, dict] = {}
        for bs, part in got.items():
            if not isinstance(part, dict) or part.get("kind") != "agg":
                continue
            used = sorted(part.get("dicts") or {})
            try:
                mapped = self.dict_sync.remap_partial(
                    sid, tname, dict(part), local_dicts)
            except DictSyncError:
                with self._lock:
                    self.counters["remap_failures"] += 1
                continue
            if used:
                # re-label with LOCAL dictionary states: after the
                # remap the ids ARE local ids (and the remap's encode
                # side effect grew the local dict to cover them), so
                # the slice now folds with locally-scanned ones
                states, ok = {}, True
                for col in used:
                    d = local_dicts.get(col)
                    if d is None:
                        ok = False
                        break
                    g, ln, _v = d.sync_state()
                    states[col] = [g, ln]
                if not ok:
                    continue
                mapped["dicts"] = states
            out[int(bs)] = mapped
        with self._lock:
            self.counters["fetched_buckets"] += len(out)
        if self._hop is not None:
            self._hop.account(emitted=1, delivered=1)
        fetch_sp.annotate(outcome="ok", fetched=len(out))
        fetch_sp.finish()
        return out

    def _call(self, addr: str, body: dict):
        headers = {"Content-Type": "application/json"}
        if self.api_token:
            headers["X-DF-Token"] = self.api_token
        req = urllib.request.Request(
            f"http://{addr}/v1/cache/partial",
            data=json.dumps(body).encode(), headers=headers)
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            return wire.decode_cache_partial(r.read())

    # -- server side ---------------------------------------------------------

    def _reject(self) -> dict:
        with self._lock:
            self.counters["serve_rejects"] += 1
        return {"buckets": {}}

    def serve(self, body: dict) -> dict:
        """POST /v1/cache/partial: answer with every requested bucket
        this node holds warm AND currently valid, plus the dict delta
        the requester needs to remap our ids."""
        tname = str(body.get("table", ""))
        tok = str(body.get("pub_token", ""))
        wanted = [int(b) for b in (body.get("buckets") or [])]
        if self.readtier is None or not wanted or not tok:
            return {"buckets": {}}
        if self.readtier.pub_token(tname) != tok:
            return self._reject()
        try:
            table = self.db.table(tname)
        except KeyError:
            return {"buckets": {}}
        if not self._pure(table):
            return self._reject()
        parts = self.query_cache.peek_buckets(
            table, str(body.get("sql", "")),
            key_variants(body.get("org")), wanted)
        if not parts:
            return {"buckets": {}}
        # one delta covering every returned slice: per-col max len (gens
        # are equal across slices — peek validated them against the
        # current table state)
        need: dict[str, list] = {}
        for part in parts.values():
            for col, st in (part.get("dicts") or {}).items():
                g, ln = int(st[0]), int(st[1])
                cur = need.get(col)
                if cur is None:
                    need[col] = [g, ln]
                elif cur[0] != g:
                    return self._reject()
                else:
                    cur[1] = max(cur[1], ln)
        out: dict = {"buckets": {str(b): p for b, p in parts.items()}}
        if need:
            sync = build_sync(table, need, body.get("dict_known") or {})
            if sync is None:
                return self._reject()
            out["dict_sync"] = sync
        with self._lock:
            self.counters["served"] += 1
            self.counters["served_buckets"] += len(parts)
        if self._hop is not None:
            self._hop.account(emitted=1, delivered=1)
        return out

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self.counters)
