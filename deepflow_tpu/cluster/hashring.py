"""Consistent-hash ownership: agent_id -> ordered [primary, replica...].

Reference analog: the controller's Trisolaris agent->analyzer assignment
(controller/trisolaris + controller/monitor rebalance), upgraded from
rendezvous preference to a consistent-hash ring with virtual nodes so
membership changes move only ~1/N of the agents, plus replication: each
agent owns an ordered shard set of size R, ships HIGH/MID frames to all
of them, and queries dedup replica copies back down to exactly one.

Three pieces live here:

* ``HashRing`` — the ring itself. Deterministic (md5, not Python's
  seeded hash), epoch-versioned, carrying a bounded per-epoch membership
  history so rows tagged with an older ring_epoch are still claimed by
  an owner that actually HOLDS them after a rebalance. Adoption is
  fenced: a snapshot is adopted only if its (election token, epoch) pair
  is strictly newer, so a deposed leader's stale ring can never clobber
  the current one.
* ``claim_mask`` / ``ClaimTableView`` / ``ClaimDbView`` — query-time
  replica dedup. Every ingested row is tagged (owner_shard, ring_epoch);
  a row is REPORTED by exactly one shard: the first owner (in the ring
  order of the row's epoch) that is alive for this query. Rows with
  ring_epoch == 0 predate replication (or were written by a server-local
  sink) and exist in exactly one copy — their holder always reports
  them, which keeps healthy-cluster results byte-identical to the
  pre-replication single-copy behavior.
"""

from __future__ import annotations

import bisect
import hashlib
import struct

import numpy as np

DEFAULT_VNODES = 64
DEFAULT_REPLICATION = 2
HISTORY_EPOCHS = 16      # per-epoch member sets kept for old-row claims


def _h64(key: str) -> int:
    return struct.unpack_from(">Q", hashlib.md5(key.encode()).digest())[0]


class HashRing:
    """Epoch-versioned consistent-hash ring over shard ids.

    members: {shard_id: {"addr": query_addr, "ingest": ingest_addr}}.
    Placement depends ONLY on shard ids (vnode keys are ``sid:i``), so
    every node that knows an epoch's member ids computes identical
    owner lists — the property the query-time claim filter relies on.
    """

    def __init__(self, members: dict, replication: int = DEFAULT_REPLICATION,
                 vnodes: int = DEFAULT_VNODES, epoch: int = 1,
                 token: int = 0, history: dict | None = None) -> None:
        self.members = {int(s): dict(m) for s, m in members.items()}
        self.replication = max(1, int(replication))
        self.vnodes = max(1, int(vnodes))
        self.epoch = int(epoch)
        self.token = int(token)
        self.history = {int(e): sorted(int(s) for s in ids)
                        for e, ids in (history or {}).items()}
        self.history[self.epoch] = sorted(self.members)
        self._points: dict[tuple, tuple[list, list]] = {}
        self._owner_cache: dict[tuple, list[int]] = {}

    # -- placement ----------------------------------------------------------

    def _ring_points(self, ids: tuple) -> tuple[list, list]:
        cached = self._points.get(ids)
        if cached is None:
            pts = sorted((_h64(f"{sid}:{i}"), sid)
                         for sid in ids for i in range(self.vnodes))
            cached = self._points[ids] = ([h for h, _ in pts],
                                          [s for _, s in pts])
        return cached

    def _owners_for(self, agent_id: int, ids: tuple) -> list[int]:
        if not ids:
            return []
        key = (agent_id, ids)
        owners = self._owner_cache.get(key)
        if owners is not None:
            return owners
        hashes, sids = self._ring_points(ids)
        i = bisect.bisect_right(hashes, _h64(f"agent:{agent_id}"))
        owners, seen = [], set()
        for step in range(len(sids)):
            sid = sids[(i + step) % len(sids)]
            if sid not in seen:
                seen.add(sid)
                owners.append(sid)
                if len(owners) >= min(self.replication, len(ids)):
                    break
        self._owner_cache[key] = owners
        return owners

    def owners(self, agent_id: int) -> list[int]:
        """Ordered [primary, replica...] shard ids under the CURRENT epoch."""
        return self._owners_for(int(agent_id), tuple(sorted(self.members)))

    def owners_at(self, agent_id: int, epoch: int) -> list[int]:
        """Owner order under a historical epoch's member set (rows keep
        the epoch they were ingested at). Unknown/evicted epochs fall
        back to the current members — the documented approximation for
        rows older than HISTORY_EPOCHS rebalances."""
        ids = self.history.get(int(epoch))
        if ids is None:
            return self.owners(agent_id)
        return self._owners_for(int(agent_id), tuple(ids))

    def ingest_addrs(self, agent_id: int) -> list[str]:
        """Owner ingest addresses in ring order — what the controller
        pushes down the synchronizer's analyzer_addrs path."""
        return [self.members[sid]["ingest"] for sid in self.owners(agent_id)
                if self.members.get(sid, {}).get("ingest")]

    def claimant(self, agent_id: int, epoch: int, alive: set) -> int | None:
        """The one shard that reports agent_id's epoch-tagged rows: its
        first ALIVE owner. None = every owner is dead (uncovered)."""
        for sid in self.owners_at(agent_id, epoch):
            if sid in alive:
                return sid
        return None

    # -- query-time claim filtering -----------------------------------------

    def claim_mask(self, agent_arr: np.ndarray, epoch_arr: np.ndarray,
                   self_shard: int, alive: set) -> np.ndarray:
        """Boolean row mask: rows this shard reports. ring_epoch == 0
        rows (single-copy, pre-replication) always pass; replicated rows
        pass iff this shard is their claimant."""
        mask = epoch_arr == 0
        if mask.all():
            return mask
        rest = ~mask
        pairs = np.unique(
            np.stack([agent_arr[rest].astype(np.int64),
                      epoch_arr[rest].astype(np.int64)], axis=1), axis=0)
        for a, e in pairs:
            if self.claimant(int(a), int(e), alive) == self_shard:
                mask |= (agent_arr == a) & (epoch_arr == e)
        return mask

    # -- coverage ------------------------------------------------------------

    def all_member_ids(self) -> set:
        ids = set(self.members)
        for hist in self.history.values():
            ids.update(hist)
        return ids

    def covers(self, dead: set) -> bool:
        """True when every agent still has >= 1 alive owner in EVERY
        epoch this ring remembers: any R-1 simultaneous failures among
        ring members are covered (each owner list holds R distinct
        shards). A dead shard the ring never knew holds only
        single-copy rows — never covered."""
        if not dead:
            return True
        if not dead <= self.all_member_ids():
            return False
        return len(dead) <= self.replication - 1

    # -- versioning / wire ---------------------------------------------------

    def newer_than(self, other: "HashRing | None") -> bool:
        """Fencing order: election token first (a deposed leader's ring
        loses to the new leader's regardless of epoch), epoch second."""
        if other is None:
            return True
        return (self.token, self.epoch) > (other.token, other.epoch)

    def snapshot(self) -> dict:
        return {
            "epoch": self.epoch, "token": self.token,
            "replication": self.replication, "vnodes": self.vnodes,
            "members": [{"shard_id": sid, **m}
                        for sid, m in sorted(self.members.items())],
            "history": {str(e): ids
                        for e, ids in sorted(self.history.items())},
        }

    @classmethod
    def from_snapshot(cls, snap: dict) -> "HashRing":
        members = {int(m["shard_id"]): {"addr": m.get("addr", ""),
                                        "ingest": m.get("ingest", "")}
                   for m in snap.get("members", [])}
        return cls(members,
                   replication=int(snap.get("replication",
                                            DEFAULT_REPLICATION)),
                   vnodes=int(snap.get("vnodes", DEFAULT_VNODES)),
                   epoch=int(snap.get("epoch", 1)),
                   token=int(snap.get("token", 0)),
                   history=snap.get("history"))

    @classmethod
    def build(cls, prev: "HashRing | None", members: dict,
              replication: int, token: int,
              vnodes: int = DEFAULT_VNODES) -> "HashRing":
        """Leader-side (re)build: returns ``prev`` unchanged when the
        member set/addrs match (no spurious epoch bumps on heartbeats);
        otherwise a new ring at epoch+1 carrying ``prev``'s bounded
        history, stamped with the leader's fencing token."""
        norm = {int(s): {"addr": m.get("addr", ""),
                         "ingest": m.get("ingest", "")}
                for s, m in members.items()}
        if prev is not None and prev.members == norm \
                and prev.replication == int(replication):
            return prev
        epoch = (prev.epoch + 1) if prev is not None else 1
        history = dict(prev.history) if prev is not None else {}
        for e in sorted(history)[:max(0, len(history)
                                      - (HISTORY_EPOCHS - 1))]:
            del history[e]
        return cls(norm, replication=replication, vnodes=vnodes,
                   epoch=epoch, token=token, history=history)


class ClaimTableView:
    """Read-only ColumnarTable facade that hides replica copies: only
    rows this shard claims (see HashRing.claim_mask) appear in
    snapshot()/column_concat()/len(). Tables without the universal
    (agent_id, ring_epoch) tags pass through untouched. Everything else
    delegates to the wrapped table, so the DF-SQL/PromQL/Tempo engines
    run on it unmodified."""

    def __init__(self, table, ring: HashRing, self_shard: int,
                 alive: set) -> None:
        self._table = table
        self._ring = ring
        self._shard = int(self_shard)
        self._alive = set(alive)

    def _claim(self, ch):
        agents = ch.get("agent_id") if ch else None
        epochs = ch.get("ring_epoch") if ch else None
        if agents is None or epochs is None:
            return ch
        m = self._ring.claim_mask(agents, epochs, self._shard,
                                  self._alive)
        return ch if m.all() else {k: v[m] for k, v in ch.items()}

    def snapshot(self) -> list:
        return [self._claim(ch) for ch in self._table.snapshot()]

    def scan_units(self) -> list:
        """Claim-filtered scan units. MUST be overridden here, not left
        to __getattr__ delegation: the engine scans through scan_units,
        and the raw table's units would leak replica copies. A segment's
        zone map and skip indexes stay attached — both are necessary
        conditions over the full chunk, so they remain sound for the
        claimed subset."""
        return [(self._claim(ch), z, seg)
                for ch, z, seg in self._table.scan_units()]

    def column_concat(self, names, mask_chunks=None, chunks=None):
        if chunks is None:
            chunks = self.snapshot()
        return self._table.column_concat(names, mask_chunks=mask_chunks,
                                         chunks=chunks)

    def __len__(self) -> int:
        return sum(len(next(iter(ch.values()))) if ch else 0
                   for ch in self.snapshot())

    def __getattr__(self, name: str):
        return getattr(self._table, name)


class ClaimDbView:
    """Database facade returning ClaimTableViews — handed to the query
    engines on the shard-exec path so every federated partial is
    replica-deduped at the source."""

    def __init__(self, db, ring: HashRing, self_shard: int,
                 alive: set) -> None:
        self._db = db
        self._ring = ring
        self._shard = int(self_shard)
        self._alive = set(alive)

    def table(self, name: str):
        return ClaimTableView(self._db.table(name), self._ring,
                              self._shard, self._alive)

    def tables(self) -> list:
        return self._db.tables()

    def __getattr__(self, name: str):
        return getattr(self._db, name)


def claim_db_from_body(body: dict, db, self_shard: int):
    """Shard-exec helper: when the coordinator shipped a ring snapshot
    and alive set in the op body, answer from the claim-filtered view;
    otherwise (pre-replication coordinator) answer raw."""
    snap = body.get("ring")
    if not snap:
        return db
    ring = HashRing.from_snapshot(snap)
    alive = set(int(s) for s in body.get("alive") or [])
    return ClaimDbView(db, ring, self_shard, alive)
