"""Cross-shard dictionary synchronization for int-key federation.

SmartEncoding ids are shard-local: "svc-a" may be id 7 on one shard and
id 91 on another. To merge encoded partials without decoding every group
key to strings, the coordinator mirrors each shard dictionary's string
prefix and keeps a memoized remap table shard-id -> LOCAL-dict-id. Merge
space is always the coordinator's own table dictionaries, so the
presentation edge decodes exactly as it does for local queries.

Protocol (rides the existing sql_partial scatter, see server/querier.py
and cluster/federation.py):

- coordinator request carries ``"dict_known": {shard: {col: [gen, len]}}``
  — the prefix of each shard dictionary it already mirrors;
- shard reply carries ``"dict_sync": {col: {"gen", "len", "base",
  "delta": [strings]}}`` — only the strings past ``base``; a gen change
  (shard-side compaction/reload rebinds ids) ships ``base=0``, a full
  resync;
- the coordinator applies deltas, then remaps every id column in the
  partial before engine.merge_partials().

Dictionaries grow append-only within a gen, so a delta is a pure
extension and previously-built remap entries stay valid; only the new
tail is encoded into the local dictionary.
"""

from __future__ import annotations

import threading

import numpy as np


class DictSyncError(Exception):
    """Shard partial references ids the mirror cannot cover (malformed
    delta or gen race) — the caller treats the shard result as failed."""


class DictSync:
    """Coordinator-side shard-dictionary mirrors + id remap tables."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # (shard_id, table, col) -> {"gen": int, "strings": [str, ...]}
        self._mirrors: dict[tuple, dict] = {}
        # (shard_id, table, col) -> {"gen", "local_id", "local_gen",
        #                            "n", "arr"}: shard id -> local id
        self._remaps: dict[tuple, dict] = {}
        self.counters = {"deltas_applied": 0, "strings_synced": 0,
                         "full_resyncs": 0, "remap_rebuilds": 0,
                         "ids_remapped": 0}

    def known_state(self, shard_id: int, table: str) -> dict:
        """{col: [gen, len]} of mirrored prefixes, for the request body."""
        with self._lock:
            return {col: [m["gen"], len(m["strings"])]
                    for (sh, tb, col), m in self._mirrors.items()
                    if sh == shard_id and tb == table}

    def apply_sync(self, shard_id: int, table: str, col: str,
                   sync: dict) -> bool:
        """Fold one shard dict_sync delta into the mirror."""
        try:
            gen, ln = int(sync["gen"]), int(sync["len"])
            base = int(sync["base"])
            delta = list(sync.get("delta") or [])
        except (KeyError, TypeError, ValueError):
            return False
        k = (shard_id, table, col)
        with self._lock:
            m = self._mirrors.get(k)
            if m is None or m["gen"] != gen or base != len(m["strings"]):
                if base != 0:
                    # delta against a prefix we don't hold — drop the
                    # mirror so the next round requests a full resync
                    self._mirrors.pop(k, None)
                    self._remaps.pop(k, None)
                    return False
                if m is not None:
                    self.counters["full_resyncs"] += 1
                m = self._mirrors[k] = {"gen": gen, "strings": []}
                self._remaps.pop(k, None)
            m["strings"].extend(delta)
            if len(m["strings"]) != ln:
                self._mirrors.pop(k, None)
                self._remaps.pop(k, None)
                return False
            self.counters["deltas_applied"] += 1
            self.counters["strings_synced"] += len(delta)
            return True

    def _remap_array(self, shard_id: int, table: str, col: str,
                     local_dict, want_gen: int, need_len: int):
        """shard-id -> local-id uint32 table covering the mirror, or None
        when the mirror is absent/short/stale for `want_gen`."""
        k = (shard_id, table, col)
        with self._lock:
            m = self._mirrors.get(k)
            if m is None or m["gen"] != want_gen or \
                    len(m["strings"]) < need_len:
                return None
            strings = m["strings"]
            lgen = local_dict.gen
            r = self._remaps.get(k)
            if (r is None or r["gen"] != m["gen"]
                    or r["local_id"] != id(local_dict)
                    or r["local_gen"] != lgen):
                r = self._remaps[k] = {
                    "gen": m["gen"], "local_id": id(local_dict),
                    "local_gen": lgen, "n": 0,
                    "arr": np.empty(0, dtype=np.uint32)}
                self.counters["remap_rebuilds"] += 1
            if r["n"] < len(strings):
                ext = np.fromiter(
                    (local_dict.encode(s) for s in strings[r["n"]:]),
                    dtype=np.uint32, count=len(strings) - r["n"])
                r["arr"] = np.concatenate([r["arr"], ext])
                r["n"] = len(strings)
            return r["arr"]

    def remap_partial(self, shard_id: int, table: str, partial: dict,
                      local_dicts: dict) -> dict:
        """Map every dictionary-id column of an encoded partial into the
        coordinator's local dictionaries (captured `local_dicts` snapshot
        so a concurrent local compaction can't skew the merge). Returns a
        new partial ready for the vectorized merge; partials with no
        encoded dict columns pass through untouched."""
        dicts = partial.get("dicts") or {}
        for col, sync in (partial.get("dict_sync") or {}).items():
            self.apply_sync(shard_id, table, col, sync)
        if not dicts or partial.get("kind") != "agg":
            out = dict(partial)
            out.pop("dict_sync", None)
            return out

        def map_ids(col: str, ids: np.ndarray) -> np.ndarray:
            local = local_dicts.get(col)
            if local is None:
                raise DictSyncError(
                    f"no local dictionary for column {col!r}")
            gen, ln = (int(x) for x in dicts.get(col, (0, 0)))
            need = max(ln, int(ids.max(initial=0)) + 1 if len(ids) else 0)
            arr = self._remap_array(shard_id, table, col, local, gen, need)
            if arr is None:
                raise DictSyncError(
                    f"mirror for shard {shard_id} col {col!r} does not "
                    f"cover gen {gen} len {need}")
            out = arr[ids.astype(np.int64)]
            with self._lock:
                self.counters["ids_remapped"] += len(out)
            return out

        def map_col(c):
            if isinstance(c, dict) and "e" in c:
                ids = np.asarray(c["ids"], dtype=np.uint32)
                return {"e": c["e"], "ids": map_ids(c["e"], ids)}
            return c

        out = dict(partial)
        out["keys"] = [map_col(c) for c in partial.get("keys", [])]
        out["items"] = {k: map_col(v)
                        for k, v in partial.get("items", {}).items()}
        sites = {}
        for sk, st in partial.get("sites", {}).items():
            if isinstance(st, dict) and "ed" in st:
                sets = st["sets"]
                flat = np.asarray([i for g in sets for i in g],
                                  dtype=np.uint32)
                mapped = (map_ids(st["ed"], flat) if len(flat)
                          else flat)
                splits = np.cumsum([len(g) for g in sets])[:-1]
                sites[sk] = {"ed": st["ed"],
                             "sets": [p.astype(np.int64).tolist()
                                      for p in np.split(mapped, splits)]}
            else:
                sites[sk] = st
        out["sites"] = sites
        out.pop("dict_sync", None)
        out.pop("dicts", None)
        return out

    def snapshot(self) -> dict:
        with self._lock:
            return {"mirrors": len(self._mirrors), **self.counters}


def build_sync(table, cols: dict, known: dict) -> dict | None:
    """Shard-side half: delta of each used dictionary past what the
    coordinator says it knows. `cols` is the partial's {col: [gen, len]}
    manifest; `known` the coordinator's {col: [gen, len]} claim. Returns
    the dict_sync payload, or None if a dictionary flipped gen since the
    partial was built (caller re-runs decoded)."""
    out = {}
    for col, (pgen, plen) in cols.items():
        d = table.dicts.get(col)
        if d is None:
            return None
        gen, ln, _ver = d.sync_state()
        if gen != int(pgen):
            return None  # compaction landed between build and reply
        kgen, klen = (int(x) for x in (known.get(col) or (-1, 0)))
        base = klen if kgen == gen and klen <= ln else 0
        out[col] = {"gen": gen, "len": ln, "base": base,
                    "delta": d.strings_slice(base, ln)}
    return out
