"""Cluster membership: seed-anchored join/heartbeat with gossip readback.

Reference analog: controller node registration + the genesis sync that
lets every DeepFlow component read one authoritative node list. Peers
POST /v1/cluster/join to the seed (the leader controller's querier
port) on an interval; every join response carries the seed's full
versioned directory, which the joiner adopts — so any node, and dfctl,
can answer GET /v1/cluster/peers with the same picture after one
heartbeat round-trip.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request
from dataclasses import dataclass, field

log = logging.getLogger("df.cluster")

DEFAULT_TTL_S = 15.0          # peer considered dead after this silence
DEFAULT_HEARTBEAT_S = 2.0


@dataclass
class Peer:
    shard_id: int
    addr: str                 # "host:query_port" serving /v1/shard/exec
    epoch: int                # process start time (ns) — restarts bump it
    last_seen_ns: int = 0
    ingest_addr: str = ""     # "host:ingest_port" for agent frame traffic
    # "ingest" owns a slice of the agent fleet (hash ring + scatter
    # target); "querier" is a stateless read replica — it answers
    # coordinator queries but must NEVER be placed in the ingest ring
    # or scattered to for shard partials (satellite fix: every joiner
    # used to be assumed to own ingest). Peers from pre-role nodes
    # deserialize as ingest, preserving old behavior.
    role: str = "ingest"

    def to_dict(self) -> dict:
        return {"shard_id": self.shard_id, "addr": self.addr,
                "epoch": self.epoch, "last_seen_ns": self.last_seen_ns,
                "ingest_addr": self.ingest_addr, "role": self.role}

    @classmethod
    def from_dict(cls, d: dict) -> "Peer":
        return cls(shard_id=int(d["shard_id"]), addr=str(d["addr"]),
                   epoch=int(d.get("epoch", 0)),
                   last_seen_ns=int(d.get("last_seen_ns", 0)),
                   ingest_addr=str(d.get("ingest_addr", "")),
                   role=str(d.get("role") or "ingest"))


@dataclass
class PeerDirectory:
    """Versioned peer list. The version bumps only on membership CHANGE
    (new shard, address move, epoch bump = restart), not on heartbeats,
    so watchers can cheaply detect topology changes."""

    _peers: dict[int, Peer] = field(default_factory=dict)
    version: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def upsert(self, peer: Peer) -> bool:
        with self._lock:
            cur = self._peers.get(peer.shard_id)
            changed = (cur is None or cur.addr != peer.addr
                       or cur.epoch != peer.epoch
                       or cur.ingest_addr != peer.ingest_addr
                       or cur.role != peer.role)
            if changed:
                self.version += 1
            peer.last_seen_ns = peer.last_seen_ns or time.time_ns()
            self._peers[peer.shard_id] = peer
            return changed

    def adopt(self, snap: dict) -> None:
        """Replace local state with a (seed-authored) snapshot, keeping
        the freshest last_seen per shard."""
        with self._lock:
            if int(snap.get("version", 0)) < self.version:
                return
            incoming = {}
            for d in snap.get("peers", []):
                p = Peer.from_dict(d)
                cur = self._peers.get(p.shard_id)
                if cur is not None and cur.last_seen_ns > p.last_seen_ns:
                    p.last_seen_ns = cur.last_seen_ns
                incoming[p.shard_id] = p
            self._peers = incoming
            self.version = int(snap.get("version", 0))

    def snapshot(self) -> dict:
        with self._lock:
            return {"version": self.version,
                    "peers": [p.to_dict() for _, p in
                              sorted(self._peers.items())]}

    def alive(self, ttl_s: float = DEFAULT_TTL_S,
              exclude_shard: int | None = None) -> list[Peer]:
        horizon = time.time_ns() - int(ttl_s * 1e9)
        with self._lock:
            return [p for _, p in sorted(self._peers.items())
                    if p.last_seen_ns >= horizon
                    and p.shard_id != exclude_shard]


class ClusterMembership:
    """One node's view: local identity + join loop against the seed.

    A node with no seed (or whose advertise addr IS the seed) acts as
    the seed: its directory is authoritative and serves joins."""

    def __init__(self, shard_id: int, advertise: str,
                 seed: str | None = None,
                 heartbeat_s: float = DEFAULT_HEARTBEAT_S,
                 telemetry=None, role: str = "ingest") -> None:
        self.shard_id = shard_id
        self.advertise = advertise
        self.seed = (seed or "").strip() or None
        self.epoch = time.time_ns()
        self.role = role
        self.directory = PeerDirectory()
        self.heartbeat_s = heartbeat_s
        self.telemetry = telemetry
        self.stats = {"joins": 0, "join_errors": 0, "ring_adoptions": 0}
        self.ingest_addr = ""      # set by the server once receiver binds
        self.ring = None           # adopted/authored HashRing (replication)
        self._ring_lock = threading.Lock()
        # distributed partial-aggregate cache gossip: local warm-key
        # digests ride the join exchange in both directions; the seed
        # merges every joiner's adverts and the merged map rides every
        # join response, so any node can ask "who has (table, sql, org)
        # warm?" after one heartbeat round-trip.
        self.cache_adv_local = None      # zero-arg -> list[str] digests
        self._cache_advs: dict[str, tuple[int, str]] = {}
        self._adv_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @property
    def is_seed(self) -> bool:
        return self.seed is None or self.seed == self.advertise

    def self_peer(self) -> Peer:
        return Peer(shard_id=self.shard_id, addr=self.advertise,
                    epoch=self.epoch, last_seen_ns=time.time_ns(),
                    ingest_addr=self.ingest_addr, role=self.role)

    # -- distributed partial-cache adverts ----------------------------
    def _local_advs(self) -> dict:
        fn = self.cache_adv_local
        if fn is None:
            return {}
        try:
            return {str(d): [self.shard_id, self.advertise]
                    for d in (fn() or [])}
        except Exception:
            return {}

    def _merge_advs(self, advs: dict | None) -> None:
        if not advs:
            return
        with self._adv_lock:
            for digest, ent in advs.items():
                try:
                    sid, addr = int(ent[0]), str(ent[1])
                except (TypeError, ValueError, IndexError):
                    continue
                if sid != self.shard_id:
                    self._cache_advs[str(digest)] = (sid, addr)

    def cache_adverts(self) -> dict:
        """Everything known warm, local keys included (seed view)."""
        with self._adv_lock:
            # copy under the lock: _merge_advs mutates from the
            # heartbeat/join threads while a join response reads this
            snap = dict(self._cache_advs)
        out = {d: [s, a] for d, (s, a) in snap.items()}
        out.update(self._local_advs())
        return out

    def advert_for(self, digest: str,
                   ttl_s: float = DEFAULT_TTL_S) -> tuple | None:
        """(shard_id, addr) of an ALIVE peer advertising this cache key
        digest, or None. Own adverts are excluded — a local miss is a
        local miss."""
        with self._adv_lock:
            ent = self._cache_advs.get(digest)
        if ent is None:
            return None
        alive = {p.shard_id for p in self.directory.alive(ttl_s=ttl_s)}
        return ent if ent[0] in alive else None

    # -- replication ring ---------------------------------------------
    def adopt_ring(self, snap: dict | None) -> bool:
        """Fenced, forward-only ring adoption: a snapshot wins only if
        its (election token, epoch) pair is strictly newer than what we
        hold — a deposed leader's stale ring can never clobber the
        current one. Rings ride the join exchange in BOTH directions so
        one heartbeat round-trip converges seed and joiner."""
        if not snap:
            return False
        from deepflow_tpu.cluster.hashring import HashRing
        ring = HashRing.from_snapshot(snap)
        with self._ring_lock:
            if not ring.newer_than(self.ring):
                return False
            self.ring = ring
            self.stats["ring_adoptions"] += 1
        log.info("cluster: adopted ring epoch %d (token %d, %d members)",
                 ring.epoch, ring.token, len(ring.members))
        return True

    def publish_ring(self, ring) -> bool:
        """Leader-side install of a freshly built ring (same fencing)."""
        with self._ring_lock:
            if not ring.newer_than(self.ring):
                return False
            self.ring = ring
        return True

    def ring_snapshot(self) -> dict | None:
        with self._ring_lock:
            return self.ring.snapshot() if self.ring is not None else None

    # -- seed side ----------------------------------------------------
    def handle_join(self, body: dict) -> dict:
        """Register/refresh one peer, answer with the full directory
        (and the replication ring, when one is active)."""
        peer = Peer.from_dict(body)
        peer.last_seen_ns = time.time_ns()
        if self.directory.upsert(peer):
            log.info("cluster: shard %d at %s joined (epoch %d)",
                     peer.shard_id, peer.addr, peer.epoch)
        self.adopt_ring(body.get("ring"))
        self._merge_advs(body.get("cache_adv"))
        self.directory.upsert(self.self_peer())
        out = self.directory.snapshot()
        ring = self.ring_snapshot()
        if ring is not None:
            out["ring"] = ring
        advs = self.cache_adverts()
        if advs:
            out["cache_adv"] = advs
        return out

    # -- joiner side --------------------------------------------------
    def _join_once(self) -> None:
        body = self.self_peer().to_dict()
        ring = self.ring_snapshot()
        if ring is not None:
            body["ring"] = ring
        advs = self._local_advs()
        if advs:
            body["cache_adv"] = advs
        req = urllib.request.Request(
            f"http://{self.seed}/v1/cluster/join",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=3.0) as resp:
            snap = json.loads(resp.read())
        self.directory.adopt(snap)
        self.adopt_ring(snap.get("ring"))
        self._merge_advs(snap.get("cache_adv"))
        self.stats["joins"] += 1

    def _loop(self) -> None:
        beat = (self.telemetry.heartbeat(
            "cluster.membership", interval_hint_s=self.heartbeat_s)
            if self.telemetry is not None else None)
        while not self._stop.is_set():
            if beat is not None:
                beat.beat()
            try:
                self._join_once()
            except Exception as e:
                self.stats["join_errors"] += 1
                log.debug("cluster join to %s failed: %s", self.seed, e)
            self._stop.wait(self.heartbeat_s)

    def start(self) -> "ClusterMembership":
        self.directory.upsert(self.self_peer())
        if not self.is_seed:
            self._thread = threading.Thread(
                target=self._loop, name="df-cluster-join", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def refresh_self(self) -> None:
        """Seed keeps its own last_seen fresh (joiners do via join)."""
        self.directory.upsert(self.self_peer())

    def peers(self, include_self: bool = True,
              ttl_s: float = DEFAULT_TTL_S,
              role: str | None = None) -> list[Peer]:
        self.refresh_self()
        alive = self.directory.alive(ttl_s=ttl_s)
        if role is not None:
            alive = [p for p in alive if p.role == role]
        if include_self:
            return alive
        return [p for p in alive if p.shard_id != self.shard_id]
