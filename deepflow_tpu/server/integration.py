"""Integration ingest: OTLP traces (JSON), Pyroscope-style profiles, app logs.

Reference analog: agent/src/integration_collector.rs (OTLP :643, Pyroscope
ingest :780, app logs :828) + server/ingester/flow_log OTel decoding. Here
the endpoints live on the server's querier HTTP port; agents can also proxy
to them.
"""

from __future__ import annotations

import json
import logging
import time

from deepflow_tpu.store.db import Database

log = logging.getLogger("df.integration")


def _attr_map(attrs: list) -> dict:
    out = {}
    for a in attrs or []:
        v = a.get("value", {})
        out[a.get("key", "")] = (
            v.get("stringValue") or v.get("intValue")
            or v.get("doubleValue") or v.get("boolValue") or "")
    return out


class IntegrationAPI:
    def __init__(self, db: Database) -> None:
        self.db = db
        self.stats = {"otlp_spans": 0, "profiles": 0, "app_logs": 0}

    # -- OTLP/HTTP JSON traces (POST /api/v1/otlp/traces) --------------------

    def ingest_otlp_traces(self, body: dict) -> dict:
        table = self.db.table("flow_log.l7_flow_log")
        rows = []
        if not isinstance(body, dict):
            raise ValueError("OTLP body must be a JSON object")
        for rs in body.get("resourceSpans", []):
            if not isinstance(rs, dict):
                raise ValueError("resourceSpans entries must be objects")
            res_attrs = _attr_map(rs.get("resource", {}).get("attributes"))
            service = str(res_attrs.get("service.name", ""))
            for ss in rs.get("scopeSpans", rs.get("instrumentationLibrarySpans", [])):
                for span in ss.get("spans", []):
                    attrs = _attr_map(span.get("attributes"))
                    start = int(span.get("startTimeUnixNano", 0))
                    end = int(span.get("endTimeUnixNano", start))
                    code = int(span.get("status", {}).get("code", 0))
                    status = {0: 0, 1: 1, 2: 3}.get(code, 0)
                    http_code = int(attrs.get("http.status_code", 0) or 0)
                    rows.append({
                        "time": start,
                        "app_service": service,
                        "l7_protocol": 3 if str(
                            attrs.get("rpc.system", "")) == "grpc" else 1,
                        "request_type": str(
                            attrs.get("http.method",
                                      attrs.get("rpc.method", ""))),
                        "endpoint": span.get("name", ""),
                        "request_resource": str(
                            attrs.get("http.target",
                                      attrs.get("url.path", ""))),
                        "request_domain": str(
                            attrs.get("http.host",
                                      attrs.get("server.address", ""))),
                        "response_status": status,
                        "response_code": http_code,
                        "response_duration": max(0, end - start),
                        "trace_id": span.get("traceId", ""),
                        "span_id": span.get("spanId", ""),
                        "parent_span_id": span.get("parentSpanId", ""),
                    })
        table.append_rows(rows)
        self.stats["otlp_spans"] += len(rows)
        return {"accepted_spans": len(rows)}

    # -- Pyroscope-style folded profiles (POST /api/v1/profile/ingest) -------

    def ingest_profile(self, params: dict, raw: bytes) -> dict:
        """Body: folded-stack text, one 'frame;frame;leaf <value>' per line
        (pyroscope collapsed format)."""
        name = params.get("name", "external")
        units = params.get("units", "samples")
        now = time.time_ns()
        table = self.db.table("profile.in_process_profile")
        rows = []
        for line in raw.decode("utf-8", "replace").splitlines():
            line = line.strip()
            if not line or " " not in line:
                continue
            stack, _, value = line.rpartition(" ")
            try:
                v = int(float(value))
            except ValueError:
                continue
            rows.append({
                "time": now,
                "app_service": name,
                "process_name": name,
                "event_type": 1,  # on-cpu
                "profiler": "pyroscope",
                "stack": stack,
                "value": v,
                "count": 1,
            })
        table.append_rows(rows)
        self.stats["profiles"] += len(rows)
        return {"accepted_stacks": len(rows), "units": units}

    # -- app logs (POST /api/v1/log) -----------------------------------------

    def ingest_app_log(self, body: dict) -> dict:
        table = self.db.table("event.event")
        entries = body if isinstance(body, list) else [body]
        entries = [e for e in entries if isinstance(e, dict)]
        rows = [{
            "time": int(e.get("timestamp_ns", time.time_ns())),
            "event_type": "app-log",
            "resource_type": "log",
            "resource_name": str(e.get("service", "")),
            "description": str(e.get("message", ""))[:1024],
            "attrs": json.dumps(
                {k: str(v) for k, v in e.items()
                 if k not in ("message", "timestamp_ns")},
                sort_keys=True),
        } for e in entries]
        table.append_rows(rows)
        self.stats["app_logs"] += len(rows)
        return {"accepted": len(rows)}
