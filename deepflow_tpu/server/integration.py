"""Integration ingest: OTLP traces (JSON), Pyroscope-style profiles, app logs.

Reference analog: agent/src/integration_collector.rs (OTLP :643, Pyroscope
ingest :780, app logs :828) + server/ingester/flow_log OTel decoding. Here
the endpoints live on the server's querier HTTP port; agents can also proxy
to them.
"""

from __future__ import annotations

import json
import logging
import time

from deepflow_tpu.store.db import Database

log = logging.getLogger("df.integration")


def _int0(v) -> int:
    """Best-effort int from user-controlled tag values; bad data must not
    poison the rest of the ingest batch."""
    try:
        return int(v)
    except (TypeError, ValueError):
        return 0


def _status_from_http(code: int) -> int:
    # RESPONSE_STATUS: 0 unknown, 1 ok, 2 client_error, 3 server_error
    if code >= 500:
        return 3
    if code >= 400:
        return 2
    return 1 if code else 0


def _any_value_str(v) -> str:
    """OTLP AnyValue -> display string: plain scalars verbatim, structured
    bodies (kvlist/array/bytes) as JSON — a structured-logging client's
    body must not silently become empty."""
    if not isinstance(v, dict):
        return "" if v is None else str(v)
    for key in ("stringValue", "intValue", "doubleValue", "boolValue"):
        if key in v:
            return str(v[key])
    if "kvlistValue" in v or "arrayValue" in v or "bytesValue" in v:
        return json.dumps(v, sort_keys=True)
    return ""


def _attr_map(attrs: list) -> dict:
    out = {}
    for a in attrs or []:
        v = a.get("value", {})
        out[a.get("key", "")] = (
            v.get("stringValue") or v.get("intValue")
            or v.get("doubleValue") or v.get("boolValue") or "")
    return out


class IntegrationAPI:
    def __init__(self, db: Database, exporters=None,
                 prom_encoder=None, trace_trees=None) -> None:
        self.db = db
        self.exporters = exporters
        self.trace_trees = trace_trees  # TraceTreeBuilder (optional)
        # SmartEncoding allocator: the controller's PromEncoder in a
        # combined binary, a GrpcPromEncoderClient on remote ingest nodes,
        # or a process-local PromEncoder standalone (ids still stable
        # within the node)
        if prom_encoder is None:
            from deepflow_tpu.server.prom_encoder import PromEncoder
            prom_encoder = PromEncoder()
        self.prom_encoder = prom_encoder
        self._known_set_ids: set[int] = set()
        self._seeded = False
        self.stats = {"otlp_spans": 0, "profiles": 0, "app_logs": 0,
                      "telegraf_rows": 0, "prom_samples": 0,
                      "datadog_spans": 0, "skywalking_spans": 0}

    def _write(self, table_name: str, rows: list[dict]) -> None:
        """HTTP-ingested rows join the re-export pipeline too (same path as
        agent telemetry — exporters must see a consistent table view)."""
        self.db.table(table_name).append_rows(rows)
        if self.exporters is not None and rows:
            self.exporters.feed(table_name, rows)
        if (self.trace_trees is not None
                and table_name == "flow_log.l7_flow_log"):
            from deepflow_tpu.store.schema import L7_PROTOS, RESPONSE_STATUS
            from deepflow_tpu.server.tracetree import span_from_l7
            for r in rows:
                tid = r.get("trace_id", "")
                if not tid:
                    continue
                d = dict(r)
                # integration rows carry enum CODES; persist labels
                for key, labels in (("l7_protocol", L7_PROTOS),
                                    ("response_status", RESPONSE_STATUS)):
                    v = d.get(key, 0)
                    if isinstance(v, int):
                        d[key] = (labels[v] if 0 <= v < len(labels)
                                  else "unknown")
                self.trace_trees.add_span(tid, span_from_l7(d))

    # -- OTLP/HTTP JSON traces (POST /api/v1/otlp/traces) --------------------

    def ingest_otlp_traces(self, body: dict) -> dict:
        rows = []
        if not isinstance(body, dict):
            raise ValueError("OTLP body must be a JSON object")
        for rs in body.get("resourceSpans", []):
            if not isinstance(rs, dict):
                raise ValueError("resourceSpans entries must be objects")
            res_attrs = _attr_map(rs.get("resource", {}).get("attributes"))
            service = str(res_attrs.get("service.name", ""))
            for ss in rs.get("scopeSpans", rs.get("instrumentationLibrarySpans", [])):
                for span in ss.get("spans", []):
                    attrs = _attr_map(span.get("attributes"))
                    start = int(span.get("startTimeUnixNano", 0))
                    end = int(span.get("endTimeUnixNano", start))
                    code = int(span.get("status", {}).get("code", 0))
                    status = {0: 0, 1: 1, 2: 3}.get(code, 0)
                    http_code = int(attrs.get("http.status_code", 0) or 0)
                    rows.append({
                        "time": start,
                        "app_service": service,
                        "l7_protocol": 3 if str(
                            attrs.get("rpc.system", "")) == "grpc" else 1,
                        "request_type": str(
                            attrs.get("http.method",
                                      attrs.get("rpc.method", ""))),
                        "endpoint": span.get("name", ""),
                        "request_resource": str(
                            attrs.get("http.target",
                                      attrs.get("url.path", ""))),
                        "request_domain": str(
                            attrs.get("http.host",
                                      attrs.get("server.address", ""))),
                        "response_status": status,
                        "response_code": http_code,
                        "response_duration": max(0, end - start),
                        "trace_id": span.get("traceId", ""),
                        "span_id": span.get("spanId", ""),
                        "parent_span_id": span.get("parentSpanId", ""),
                    })
        self._write("flow_log.l7_flow_log", rows)
        self.stats["otlp_spans"] += len(rows)
        return {"accepted_spans": len(rows)}

    # -- Pyroscope-style folded profiles (POST /api/v1/profile/ingest) -------

    def ingest_profile(self, params: dict, raw: bytes) -> dict:
        """Body: folded-stack text, one 'frame;frame;leaf <value>' per line
        (pyroscope collapsed format)."""
        name = params.get("name", "external")
        units = params.get("units", "samples")
        now = time.time_ns()
        rows = []
        for line in raw.decode("utf-8", "replace").splitlines():
            line = line.strip()
            if not line or " " not in line:
                continue
            stack, _, value = line.rpartition(" ")
            try:
                v = int(float(value))
            except ValueError:
                continue
            rows.append({
                "time": now,
                "app_service": name,
                "process_name": name,
                "event_type": 1,  # on-cpu
                "profiler": "pyroscope",
                "stack": stack,
                "value": v,
                "count": 1,
            })
        self._write("profile.in_process_profile", rows)
        self.stats["profiles"] += len(rows)
        return {"accepted_stacks": len(rows), "units": units}

    # -- prometheus remote-write ---------------------------------------------

    def ingest_prometheus(self, raw: bytes) -> dict:
        from deepflow_tpu.utils import snappy
        from deepflow_tpu.tpuprobe.pbwire import WireError
        try:
            data = snappy.decompress(raw)
        except snappy.SnappyError:
            data = raw  # tolerate uncompressed senders
        try:
            series = _parse_write_request(data)
        except WireError as e:
            raise ValueError(f"not a WriteRequest: {e}") from None
        if not self._seeded:
            self.seed_from_store()
        names = [name for name, _, _ in series]
        sets_json = [json.dumps(labels, sort_keys=True)
                     for _, labels, _ in series]
        # SERIES identity = metric + labels: two metrics sharing a label
        # set are different series and must not share a label_set_id
        set_keys = [f"{n}|{ls}" for n, ls in zip(names, sets_json)]
        metric_ids, set_ids = self.prom_encoder.encode(names, set_keys)
        rows = []
        dict_rows = []
        now_s = int(time.time())
        for (name, labels, samples), labels_json, mid, sid in zip(
                series, sets_json, metric_ids, set_ids):
            if sid not in self._known_set_ids:
                self._known_set_ids.add(sid)
                dict_rows.append({
                    "time": now_s, "label_set_id": sid, "metric_id": mid,
                    "metric_name": name, "labels_json": labels_json})
            for ts_ms, value in samples:
                ts_s = int(ts_ms // 1000)
                if not (0 <= ts_s < 2**32):
                    continue  # ns-unit senders would overflow the u32 column
                rows.append({
                    "time": ts_s,
                    "metric_name": name,
                    "labels_json": labels_json,
                    "metric_id": mid,
                    "label_set_id": sid,
                    "value": value,
                })
        if dict_rows:
            self.db.table("prometheus.label_sets").append_rows(dict_rows)
        self._write("prometheus.samples", rows)
        self.stats["prom_samples"] = self.stats.get("prom_samples", 0) \
            + len(rows)
        return {"accepted_samples": len(rows), "series": len(series)}

    def seed_from_store(self) -> None:
        """Restore encoder + dedup state from the persisted label_sets
        table (idempotent; runs lazily on first ingest so it sees the
        post-load table even though this object is built before load)."""
        self._seeded = True
        try:
            t = self.db.table("prometheus.label_sets")
        except KeyError:
            return
        if not len(t):
            return
        cols = t.column_concat(["label_set_id", "metric_id",
                                "metric_name", "labels_json"])
        metric_ids: dict[str, int] = {}
        set_ids: dict[str, int] = {}
        for sid, mid, mn, lj in zip(cols["label_set_id"],
                                    cols["metric_id"],
                                    cols["metric_name"],
                                    cols["labels_json"]):
            name = t.dicts["metric_name"].decode(int(mn))
            labels = t.dicts["labels_json"].decode(int(lj))
            metric_ids[name] = int(mid)
            set_ids[f"{name}|{labels}"] = int(sid)
            self._known_set_ids.add(int(sid))
        seed = getattr(self.prom_encoder, "seed", None)
        if seed is not None:  # grpc client view has no allocator to seed
            seed(metric_ids, set_ids)

    # -- app logs (POST /api/v1/log) -----------------------------------------
    # reference: server/ingester/app_log — a DEDICATED log store (not an
    # event row): untruncated body, OTLP severity, trace/span join columns.

    _SEVERITY_NUM = {"trace": 1, "debug": 5, "info": 9, "warn": 13,
                     "warning": 13, "error": 17, "fatal": 21, "crit": 21,
                     "critical": 21}

    def ingest_app_log(self, body: dict) -> dict:
        entries = body if isinstance(body, list) else [body]
        entries = [e for e in entries if isinstance(e, dict)]
        rows = []
        for e in entries:
            sev_text = str(e.get("severity", e.get("level", "")))
            sev_num = _int0(e.get("severity_number", 0)) or \
                self._SEVERITY_NUM.get(sev_text.lower(), 0)
            rows.append({
                "time": _int0(e.get("timestamp_ns") or 0) or time.time_ns(),
                "app_service": str(e.get("service", "")),
                "app_instance": str(e.get("instance", "")),
                "log_source": 1,  # app
                "severity_number": min(24, max(0, sev_num)),
                "severity_text": sev_text,
                "body": str(e.get("message", "")),
                "trace_id": str(e.get("trace_id", "")),
                "span_id": str(e.get("span_id", "")),
                "attrs": json.dumps(
                    {k: str(v) for k, v in e.items()
                     if k not in ("message", "timestamp_ns", "service",
                                  "instance", "severity", "level",
                                  "severity_number", "trace_id", "span_id")},
                    sort_keys=True),
            })
        self._write("application_log.log", rows)
        self.stats["app_logs"] += len(rows)
        return {"accepted": len(rows)}

    # -- OTLP logs (POST /api/v1/otlp/logs) ----------------------------------
    # OTLP/HTTP JSON LogsData: resourceLogs -> scopeLogs -> logRecords.

    def ingest_otlp_logs(self, body: dict) -> dict:
        if not isinstance(body, dict):
            raise ValueError("OTLP body must be a JSON object")
        rows = []
        for rl in body.get("resourceLogs", []):
            if not isinstance(rl, dict):
                raise ValueError("resourceLogs entries must be objects")
            res = rl.get("resource", {})
            if not isinstance(res, dict):
                raise ValueError("resource must be an object")
            res_attrs = _attr_map(res.get("attributes"))
            service = str(res_attrs.get("service.name", ""))
            instance = str(res_attrs.get("service.instance.id", ""))
            for sl in rl.get("scopeLogs", []):
                if not isinstance(sl, dict):
                    continue
                for rec in sl.get("logRecords", []):
                    if not isinstance(rec, dict):
                        continue
                    text = _any_value_str(rec.get("body", {}))
                    attrs = _attr_map(rec.get("attributes"))
                    ts = _int0(rec.get("timeUnixNano", 0)) or \
                        _int0(rec.get("observedTimeUnixNano", 0)) or \
                        time.time_ns()
                    rows.append({
                        "time": ts,
                        "app_service": service,
                        "app_instance": instance,
                        "log_source": 2,  # otlp
                        "severity_number": min(24, max(0, _int0(
                            rec.get("severityNumber", 0)))),
                        "severity_text": str(rec.get("severityText", "")),
                        "body": text,
                        "trace_id": str(rec.get("traceId", "")),
                        "span_id": str(rec.get("spanId", "")),
                        "attrs": json.dumps(
                            {k: str(v) for k, v in attrs.items()},
                            sort_keys=True),
                    })
        self._write("application_log.log", rows)
        self.stats["app_logs"] += len(rows)
        return {"accepted": len(rows)}

    # -- telegraf (POST /api/v1/telegraf) ------------------------------------
    # reference: agent integration_collector.rs:757 forwards Telegraf
    # influx-line-protocol posts; server ext_metrics ingester decodes them.

    def ingest_telegraf(self, raw: bytes) -> dict:
        from deepflow_tpu.utils.influxline import parse_lines
        points, bad = parse_lines(raw.decode("utf-8", "replace"))
        now = time.time_ns()
        rows = []
        for p in points:
            tag_json = json.dumps(p.tags, sort_keys=True)
            ts = p.timestamp_ns or now
            for k, v in p.fields.items():
                if isinstance(v, str):  # string fields aren't series values
                    continue
                rows.append({
                    "time": ts,
                    "metric_name": p.measurement,
                    "tag_json": tag_json,
                    "value_name": k,
                    "value": float(v),
                    # Telegraf's host tag doubles as the universal host
                    # column (it would otherwise shadow the json tag in
                    # PromQL matchers, which prefer real columns)
                    "host": p.tags.get("host", ""),
                })
        self._write("ext_metrics.metrics", rows)
        self.stats["telegraf_rows"] += len(rows)
        return {"accepted": len(rows), "bad_lines": bad}

    # -- Datadog traces (PUT/POST /v0.3/traces, /v0.4/traces) ----------------
    # reference: integration_collector.rs:893. dd-trace clients ship
    # msgpack (or JSON) bodies: a list of traces, each a list of span maps.

    def ingest_datadog(self, raw: bytes, content_type: str = "") -> dict:
        if "json" in content_type:
            traces = json.loads(raw.decode("utf-8", "replace") or "[]")
        else:
            from deepflow_tpu.utils import msgpack
            traces = msgpack.unpackb(raw) if raw else []
        if not isinstance(traces, list):
            raise ValueError("datadog body must be a list of traces")
        rows = []
        for trace in traces:
            if not isinstance(trace, list):
                continue
            for span in trace:
                if not isinstance(span, dict):
                    continue
                meta = span.get("meta") or {}
                start = _int0(span.get("start", 0))
                code = _int0(meta.get("http.status_code", 0) or 0)
                err = _int0(span.get("error", 0) or 0)
                rows.append({
                    "time": start,
                    "app_service": str(span.get("service", "")),
                    "l7_protocol": 1,
                    "request_type": str(meta.get("http.method", "")),
                    "endpoint": str(span.get("name", "")),
                    "request_resource": str(span.get("resource", "")),
                    "request_domain": str(meta.get("http.host", "")),
                    "response_status": 3 if err else
                    _status_from_http(code),
                    "response_code": code,
                    "response_duration": max(0, _int0(span.get("duration", 0))),
                    # dd ids are u64; render as 16-hex so they join
                    # OTLP-propagated w3c ids' low halves
                    "trace_id": f"{_int0(span.get('trace_id', 0)):016x}",
                    "span_id": f"{_int0(span.get('span_id', 0)):016x}",
                    "parent_span_id": f"{_int0(span.get('parent_id', 0)):016x}"
                    if span.get("parent_id") else "",
                })
        self._write("flow_log.l7_flow_log", rows)
        self.stats["datadog_spans"] += len(rows)
        return {"accepted_spans": len(rows)}

    # -- SkyWalking segments (POST /v3/segments, segment JSON) ---------------
    # reference: flow_log decoder skywalking handler + the agent-side
    # integration plugin; the JSON shape mirrors skywalking-data-collect-
    # protocol's SegmentObject.

    def ingest_skywalking(self, body) -> dict:
        segments = body if isinstance(body, list) else [body]
        rows = []
        for seg in segments:
            if not isinstance(seg, dict):
                continue
            trace_id = str(seg.get("traceId", ""))
            seg_id = str(seg.get("traceSegmentId", ""))
            service = str(seg.get("service", ""))
            spans = seg.get("spans", [])
            for span in spans if isinstance(spans, list) else []:
                if not isinstance(span, dict):
                    continue
                raw_tags = span.get("tags") or []
                tags = {str(t.get("key")): str(t.get("value"))
                        for t in raw_tags if isinstance(t, dict)} \
                    if isinstance(raw_tags, list) else {}
                start_ms = _int0(span.get("startTime", 0))
                end_ms = _int0(span.get("endTime", start_ms)) or start_ms
                sid = _int0(span.get("spanId", 0))
                parent = _int0(span.get("parentSpanId", -1))
                if parent >= 0:
                    parent_span = f"{seg_id}-{parent}"
                else:  # cross-segment link via refs
                    refs = span.get("refs") or []
                    ref = refs[0] if refs and isinstance(refs[0], dict) \
                        else {}
                    ref_seg = ref.get("parentTraceSegmentId")
                    parent_span = (f"{ref_seg}-{_int0(ref.get('parentSpanId', 0))}"
                                   if ref_seg else "")
                code = _int0(tags.get("http.status_code",
                                      tags.get("status_code", 0)) or 0)
                rows.append({
                    "time": start_ms * 1_000_000,
                    "app_service": service,
                    "l7_protocol": 1,
                    "request_type": str(tags.get("http.method", "")),
                    "endpoint": str(span.get("operationName", "")),
                    "request_resource": str(tags.get("url",
                                                     tags.get("http.url",
                                                              ""))),
                    "response_status": 3 if span.get("isError") else
                    _status_from_http(code),
                    "response_code": code,
                    "response_duration": max(0, (end_ms - start_ms)
                                             * 1_000_000),
                    "trace_id": trace_id,
                    "span_id": f"{seg_id}-{sid}",
                    "parent_span_id": parent_span,
                })
        self._write("flow_log.l7_flow_log", rows)
        self.stats["skywalking_spans"] += len(rows)
        return {"accepted_spans": len(rows)}


# -- prometheus remote-write (POST /api/v1/write) ----------------------------
# reference: server/ingester/prometheus decoder; body is snappy-compressed
# prometheus.WriteRequest protobuf (parsed with pbwire — no generated stubs)

def _parse_write_request(data: bytes) -> list[tuple[str, dict, list]]:
    """-> [(metric_name, labels, [(ts_ms, value), ...]), ...]"""
    from deepflow_tpu.tpuprobe import pbwire as w
    out = []
    for f, _, ts_buf in w.iter_fields(data):
        if f != 1 or not isinstance(ts_buf, bytes):
            continue
        labels: dict[str, str] = {}
        samples: list[tuple[int, float]] = []
        for lf, _, lv in w.iter_fields(ts_buf):
            if lf == 1 and isinstance(lv, bytes):  # Label
                ld = w.fields_dict(lv)
                labels[w.as_str(w.first(ld, 1))] = w.as_str(w.first(ld, 2))
            elif lf == 2 and isinstance(lv, bytes):  # Sample
                sd = w.fields_dict(lv)
                raw_v = w.first(sd, 1, 0)
                value = w.f64(raw_v) if isinstance(raw_v, int) else raw_v
                ts_ms = w.first(sd, 2, 0)
                if ts_ms > (1 << 62):  # zigzag not used; guard garbage
                    continue
                samples.append((ts_ms, value))
        name = labels.pop("__name__", "")
        if name and samples:
            out.append((name, labels, samples))
    return out
