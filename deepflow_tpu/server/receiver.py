"""Framed TCP/UDP receiver with per-message-type handler queues.

Reference analog: server/libs/receiver/receiver.go:424 (NewReceiver) and
:448 (RegistHandler) — one listener, a registry of per-message-type queues,
decoders consume from their queue.
"""

from __future__ import annotations

import logging
import queue
import socket
import socketserver
import threading
import time

from deepflow_tpu.codec import (
    FrameDecodeError, FrameHeader, MessageType, StreamDecoder, decode_frame)

log = logging.getLogger("df.receiver")


class Receiver:
    """Listens on TCP (and UDP) and fans frames out to registered queues."""

    def __init__(self, host: str = "127.0.0.1", port: int = 20033,
                 queue_size: int = 4096, enable_udp: bool = True,
                 telemetry=None) -> None:
        self.host = host
        self.port = port
        self._queues: dict[MessageType, queue.Queue] = {}
        self._queue_size = queue_size
        self._tcp: socketserver.ThreadingTCPServer | None = None
        self._udp_sock: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._enable_udp = enable_udp
        self.stats = {"frames": 0, "bytes": 0, "dropped": 0, "bad_frames": 0,
                      "connections": 0}
        if telemetry is None:
            from deepflow_tpu.telemetry import Telemetry
            telemetry = Telemetry("server", enabled=False)
        self.telemetry = telemetry
        self._hop = telemetry.hop("receiver")

    def register(self, msg_type: MessageType) -> queue.Queue:
        q = self._queues.get(msg_type)
        if q is None:
            q = queue.Queue(maxsize=self._queue_size)
            self._queues[msg_type] = q
        return q

    def _dispatch(self, header: FrameHeader, payload: bytes) -> None:
        """Hand one frame to its decoder queue (UDP path: one frame per
        datagram). Queue items are (enqueue_ns, LIST of (header, payload))
        so consumers see one contract for both paths and can histogram
        their queue wait."""
        self.stats["frames"] += 1
        self.stats["bytes"] += len(payload)
        self._hop.account(emitted=1)
        q = self._queues.get(header.msg_type)
        if q is None:
            self.stats["dropped"] += 1
            self._hop.account(dropped=1, reason="no_handler")
            return
        try:
            q.put_nowait((time.monotonic_ns(), [(header, payload)]))
            self._hop.account(delivered=1)
        except queue.Full:
            # backpressure stance: drop newest, count it (reference drops too)
            self.stats["dropped"] += 1
            self._hop.account(dropped=1, reason="queue_full")

    def _dispatch_many(self, frames: list[tuple[FrameHeader, bytes]]) -> None:
        """Hand all frames parsed out of one recv() to their decoder queues
        with ONE queue.put per message type — a TCP read that carried 30
        flow-log frames used to cost 30 put_nowait round trips (and 30
        queue.get wakeups on the decoder side); now it costs one."""
        by_type: dict[MessageType, list] = {}
        for header, payload in frames:
            self.stats["frames"] += 1
            self.stats["bytes"] += len(payload)
            group = by_type.get(header.msg_type)
            if group is None:
                group = by_type[header.msg_type] = []
            group.append((header, payload))
        self._hop.account(emitted=len(frames))
        enq_ns = time.monotonic_ns()
        for msg_type, group in by_type.items():
            q = self._queues.get(msg_type)
            if q is None:
                self.stats["dropped"] += len(group)
                self._hop.account(dropped=len(group), reason="no_handler")
                continue
            try:
                q.put_nowait((enq_ns, group))
                self._hop.account(delivered=len(group))
            except queue.Full:
                # backpressure stance: drop newest, count it
                self.stats["dropped"] += len(group)
                self._hop.account(dropped=len(group), reason="queue_full")

    # -- TCP -----------------------------------------------------------------

    def start(self) -> "Receiver":
        recv = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                recv.stats["connections"] += 1
                dec = StreamDecoder()
                sock = self.request
                sock.settimeout(60.0)
                while True:
                    try:
                        data = sock.recv(256 << 10)
                    except (socket.timeout, OSError):
                        return
                    if not data:
                        return
                    try:
                        frames = list(dec.feed(data))
                        if frames:
                            recv._dispatch_many(frames)
                    except FrameDecodeError as e:
                        recv.stats["bad_frames"] += 1
                        log.warning("dropping connection: %s", e)
                        return

        # NOT beaten here: the first beat records the owning thread's
        # ident for stack snapshots, and that must be the serve loop
        hb = self.telemetry.heartbeat("receiver", interval_hint_s=0.5)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

            def service_actions(inner) -> None:
                # called by serve_forever every poll (~0.5s): the accept
                # loop's own liveness, with frame count as progress
                hb.beat(progress=recv.stats["frames"])

        self._tcp = Server((self.host, self.port), Handler)
        self.port = self._tcp.server_address[1]  # resolve port 0
        t = threading.Thread(target=self._tcp.serve_forever,
                             name="df-receiver-tcp", daemon=True)
        t.start()
        self._threads.append(t)
        if self._enable_udp:
            self._start_udp()
        return self

    # -- UDP (one frame per datagram) ---------------------------------------

    def _start_udp(self) -> None:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        s.settimeout(0.5)
        self._udp_sock = s

        def run() -> None:
            while self._udp_sock is not None:
                try:
                    data, _ = s.recvfrom(64 << 10)
                except socket.timeout:
                    continue
                except OSError:
                    return
                try:
                    header, payload, consumed = decode_frame(data)
                    if consumed:
                        self._dispatch(header, payload)
                    else:
                        self.stats["bad_frames"] += 1
                except FrameDecodeError:
                    self.stats["bad_frames"] += 1

        t = threading.Thread(target=run, name="df-receiver-udp", daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        if self._tcp:
            self._tcp.shutdown()
            self._tcp.server_close()
            self._tcp = None
        if self._udp_sock:
            s, self._udp_sock = self._udp_sock, None
            s.close()
