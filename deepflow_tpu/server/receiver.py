"""Framed TCP/UDP receiver with per-message-type handler queues.

Reference analog: server/libs/receiver/receiver.go:424 (NewReceiver) and
:448 (RegistHandler) — one listener, a registry of per-message-type queues,
decoders consume from their queue.

Durable-delivery additions (this port's transport is loss-bounded, the
reference's is not): v2 frames carry a per-agent ``seq``; the receiver
tracks the highest contiguous seq per agent (``SeqAckTracker``) and
periodically writes ACK frames back down each TCP connection, which is
what lets the agent trim its retransmit window and disk spool.  The
tracker is fed by the DECODERS after a frame's rows are written (not at
enqueue time), so an acked frame has reached the store — a frame that
is dropped on a full decoder queue, or lost with the queue in a hard
server crash, was never observed and is retransmitted by the agent.
SEQ_BASE control frames ("no seq below B will ever be sent") are
handled here inline: they fast-forward the watermark past gaps the
agent declared permanently dead (agent restart, spool eviction).
"""

from __future__ import annotations

import logging
import queue
import socket
import socketserver
import threading
import time

from deepflow_tpu.codec import (
    FrameDecodeError, FrameHeader, MessageType, StreamDecoder, decode_frame,
    decode_seq_base, encode_ack, priority_of)

log = logging.getLogger("df.receiver")


class SeqAckTracker:
    """Per-agent highest-contiguous-seq bookkeeping.

    ``observe()`` is called for every accepted v2 frame; ``contiguous()``
    is what gets acked.  Out-of-order seqs (spool replay interleaving
    with live traffic) park in a bounded set until the gap fills; if the
    set overflows, the gap is declared permanent (the missing frame was
    dropped WITH ledger accounting somewhere) and the window jumps —
    liveness over completeness, but never silently: the drop that made
    the hole is already on a ledger."""

    MAX_OOS = 4096

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # agent_id -> [contiguous_seq, out_of_order_set]
        self._state: dict[int, list] = {}

    def seed(self, agent_id: int, contiguous: int) -> None:
        """Restore persisted ack state (server restart with data_dir)."""
        with self._lock:
            st = self._state.get(agent_id)
            if st is None or contiguous > st[0]:
                self._state[agent_id] = [contiguous, set()]

    def advance(self, agent_id: int, contiguous: int) -> None:
        """Forward-only watermark jump (SEQ_BASE): the agent declared
        every seq <= contiguous dead-or-delivered, so stop waiting for
        them — park-set entries below are absorbed, and parked seqs
        just above the new watermark drain into it."""
        with self._lock:
            st = self._state.get(agent_id)
            if st is None:
                self._state[agent_id] = [contiguous, set()]
                return
            contig, oos = st
            if contiguous <= contig:
                return
            contig = contiguous
            oos.difference_update({s for s in oos if s <= contig})
            while contig + 1 in oos:
                contig += 1
                oos.discard(contig)
            st[0] = contig

    def observe(self, agent_id: int, seq: int) -> None:
        with self._lock:
            st = self._state.get(agent_id)
            if st is None:
                # first frame this server lifetime anchors the window
                self._state[agent_id] = [seq, set()]
                return
            contig, oos = st
            if seq <= contig:
                return  # dup/old
            if seq == contig + 1:
                contig += 1
                while contig + 1 in oos:
                    contig += 1
                    oos.discard(contig)
                st[0] = contig
                return
            oos.add(seq)
            if len(oos) > self.MAX_OOS:
                contig = min(oos)
                oos.discard(contig)
                while contig + 1 in oos:
                    contig += 1
                    oos.discard(contig)
                st[0] = contig

    def contiguous(self, agent_id: int) -> int | None:
        with self._lock:
            st = self._state.get(agent_id)
            return st[0] if st is not None else None

    def snapshot(self) -> dict[int, int]:
        with self._lock:
            return {a: st[0] for a, st in self._state.items()}


class Receiver:
    """Listens on TCP (and UDP) and fans frames out to registered queues."""

    def __init__(self, host: str = "127.0.0.1", port: int = 20033,
                 queue_size: int = 4096, enable_udp: bool = True,
                 telemetry=None, ack_enabled: bool = True,
                 chaos=None, qos=None) -> None:
        self.host = host
        self.port = port
        # closed-loop QoS (deepflow_tpu/qos): when attached, frames are
        # admitted through per-(org, priority-class) fair queues instead
        # of being put straight onto the decoder queues
        self._qos = qos if (qos is not None and qos.enabled) else None
        self._queues: dict[MessageType, queue.Queue] = {}
        self._queue_size = queue_size
        self._tcp: socketserver.ThreadingTCPServer | None = None
        self._udp_sock: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        # live handler (thread, socket) pairs: stop() must be able to
        # force daemon handlers out and WAIT for them, or a handler can
        # enqueue a frame after the decoders drained — observed (acked)
        # but never written
        self._handlers_lock = threading.Lock()
        self._handlers: dict[threading.Thread, socket.socket] = {}
        self._stopping = False
        # round-robin connection -> lane assignment (register lanes > 1)
        self._lane_counter = 0
        self._enable_udp = enable_udp
        self.ack_enabled = ack_enabled
        self.seq_tracker = SeqAckTracker()
        # optional DedupWindow (wired by Server.start): SEQ_BASE also
        # advances its per-agent floor — safe, because acked => decoded,
        # so nothing below the announced base can still sit undecoded
        self.dedup = None
        if chaos is None:
            from deepflow_tpu.chaos import chaos_from_env
            chaos = chaos_from_env()
        self._chaos = chaos
        # recv_ns: wall time spent parsing frames out of recv chunks and
        # enqueueing them (the "recv" stage of the ingest bench's
        # per-stage breakdown; decode/dict/write are measured downstream)
        self.stats = {"frames": 0, "bytes": 0, "dropped": 0, "bad_frames": 0,
                      "connections": 0, "acks_sent": 0, "seq_bases": 0,
                      "udp_trailing_garbage": 0, "recv_ns": 0}
        # per-tenant/per-agent drop attribution: a shed batched group is
        # charged to every (org, agent, reason) it contained, never as
        # one anonymous lump — the QoS counters and the hop ledger must
        # agree per org.  Cold path only (drops), so a plain dict+lock.
        self._drop_lock = threading.Lock()
        self.drops_by_org: dict[int, dict[str, int]] = {}
        self.drops_by_agent: dict[int, dict[str, int]] = {}
        if telemetry is None:
            from deepflow_tpu.telemetry import Telemetry
            telemetry = Telemetry("server", enabled=False)
        self.telemetry = telemetry
        self._hop = telemetry.hop("receiver")

    def register(self, msg_type: MessageType, lanes: int = 1):
        """Register the decoder queue(s) for one message type.

        lanes > 1 returns a LIST of queues and spreads CONNECTIONS
        across them round-robin (UDP spreads by agent id): with one
        decoder worker pinned per lane, a single hot agent saturating
        its connection can no longer serialize every other agent behind
        one queue (ROADMAP item 5's multi-connection recv lever).
        Per-agent ordering survives because one agent speaks over one
        connection at a time, and one connection maps to one lane —
        reconnects may switch lanes, which the seq/dedup machinery
        already absorbs (same contract as a decoder-worker handoff)."""
        q = self._queues.get(msg_type)
        if q is None:
            if lanes > 1:
                q = [queue.Queue(maxsize=self._queue_size)
                     for _ in range(lanes)]
            else:
                q = queue.Queue(maxsize=self._queue_size)
            self._queues[msg_type] = q
        return q

    @staticmethod
    def _lane_q(q, lane: int):
        return q[lane % len(q)] if isinstance(q, list) else q

    def attach_qos(self, qos, flusher_backlog=None) -> None:
        """Wire the QoS facade between frame parse and the decoder
        queues (Server calls this before start(), after decoders have
        registered their queues)."""
        if qos is None or not qos.enabled:
            return
        qos.attach(self._deliver_admitted, hop=self._hop,
                   observe_seqs=self._observe_seqs,
                   decoder_fill=self.decoder_fill,
                   flusher_backlog=flusher_backlog)
        self._qos = qos

    def _account_org_drop(self, group, reason: str) -> None:
        """Attribute one shed group per (org, agent): the cold half of
        satellite 'group-drop attribution' — ledger reasons stay flat,
        the per-tenant split lives here and on /v1/health."""
        with self._drop_lock:
            for header, _ in group:
                o = self.drops_by_org.setdefault(header.org_id, {})
                o[reason] = o.get(reason, 0) + 1
                a = self.drops_by_agent.setdefault(header.agent_id, {})
                a[reason] = a.get(reason, 0) + 1

    def drop_attribution(self) -> dict:
        with self._drop_lock:
            return {
                "by_org": {str(k): dict(v)
                           for k, v in sorted(self.drops_by_org.items())},
                "by_agent": {str(k): dict(v)
                             for k, v in
                             sorted(self.drops_by_agent.items())},
            }

    def decoder_fill(self) -> float:
        """Worst decoder-queue fill fraction (PressureController
        signal)."""
        worst = 0.0
        for q in self._queues.values():
            for qq in (q if isinstance(q, list) else [q]):
                if qq.maxsize > 0:
                    worst = max(worst, qq.qsize() / qq.maxsize)
        return min(1.0, worst)

    def _deliver_admitted(self, msg_type, lane: int, enq_ns: int,
                          group: list):
        """Admission drain -> decoder queue.  Returns True (delivered:
        the drain accounts it), "dropped" (consumed by policy, already
        accounted here) or False (decoder queue full right now —
        the drain retries / sheds by class)."""
        q = self._queues.get(msg_type)
        if q is None:
            n = len(group)
            self.stats["dropped"] += n
            self._hop.account(dropped=n, reason="no_handler")
            self._account_org_drop(group, "no_handler")
            self._observe_seqs(group)
            return "dropped"
        q = self._lane_q(q, lane)
        try:
            q.put_nowait((enq_ns, group))
            return True
        except queue.Full:
            return False

    def _observe_seqs(self, frames: list[tuple[FrameHeader, bytes]]) -> None:
        """Mark seqs as handled WITHOUT a decoder pass (policy drops like
        no_handler). Normal frames are observed by their decoder after
        the rows are written, so an ack implies store presence."""
        for header, _ in frames:
            if header.seq is not None:
                self.seq_tracker.observe(header.agent_id, header.seq)

    def _handle_seq_base(self, header: FrameHeader, payload: bytes) -> None:
        """SEQ_BASE control frame: the agent will never (re)send a seq
        below base — fast-forward the watermark and the dedup floor so
        the dead gap cannot stall acks (or grow the dedup park set).
        Advancing the dedup floor is safe because acked => decoded: any
        frame below base is either already through a decoder or will
        never arrive."""
        try:
            base = decode_seq_base(payload)
        except FrameDecodeError:
            self.stats["bad_frames"] += 1
            return
        self.stats["seq_bases"] += 1
        if base <= 0:
            return
        self.seq_tracker.advance(header.agent_id, base - 1)
        if self.dedup is not None:
            self.dedup.advance_floor(header.agent_id, base - 1)

    def _dispatch(self, header: FrameHeader, payload: bytes) -> None:
        """Hand one frame to its decoder queue (UDP path: one frame per
        datagram). Queue items are (enqueue_ns, LIST of (header, payload))
        so consumers see one contract for both paths and can histogram
        their queue wait."""
        if self._qos is not None:
            # UDP lane affinity is per AGENT (no connection to pin to)
            self._dispatch_qos([(header, payload)], header.agent_id)
            return
        self.stats["frames"] += 1
        self.stats["bytes"] += len(payload)
        self._hop.account(emitted=1)
        q = self._queues.get(header.msg_type)
        if q is None:
            self.stats["dropped"] += 1
            self._hop.account(dropped=1, reason="no_handler")
            self._account_org_drop([(header, payload)], "no_handler")
            # acked anyway: "no decoder registered" is policy, not
            # pressure — a retransmit would meet the same fate
            self._observe_seqs([(header, payload)])
            return
        # UDP lane affinity is per AGENT (no connection to pin to)
        q = self._lane_q(q, header.agent_id)
        try:
            q.put_nowait((time.monotonic_ns(), [(header, payload)]))
            self._hop.account(delivered=1)
            # NOT observed here: the decoder observes after the rows are
            # written, so the eventual ack implies store presence
        except queue.Full:
            # backpressure stance: drop newest, count it — and WITHHOLD
            # the ack so a durable sender retransmits it later
            self.stats["dropped"] += 1
            self._hop.account(dropped=1, reason="queue_full")
            self._account_org_drop([(header, payload)], "queue_full")

    def _dispatch_many(self, frames: list[tuple[FrameHeader, bytes]],
                       lane: int = 0) -> None:
        """Hand all frames parsed out of one recv() to their decoder queues
        with ONE queue.put per message type — a TCP read that carried 30
        flow-log frames used to cost 30 put_nowait round trips (and 30
        queue.get wakeups on the decoder side); now it costs one.
        ``lane`` is the calling connection's affinity index (register
        with lanes > 1 to spread connections over distinct queues)."""
        if self._qos is not None:
            self._dispatch_qos(frames, lane)
            return
        by_type: dict[MessageType, list] = {}
        for header, payload in frames:
            self.stats["frames"] += 1
            self.stats["bytes"] += len(payload)
            group = by_type.get(header.msg_type)
            if group is None:
                group = by_type[header.msg_type] = []
            group.append((header, payload))
        self._hop.account(emitted=len(frames))
        enq_ns = time.monotonic_ns()
        for msg_type, group in by_type.items():
            q = self._queues.get(msg_type)
            if q is None:
                self.stats["dropped"] += len(group)
                self._hop.account(dropped=len(group), reason="no_handler")
                self._account_org_drop(group, "no_handler")
                self._observe_seqs(group)
                continue
            q = self._lane_q(q, lane)
            try:
                q.put_nowait((enq_ns, group))
                self._hop.account(delivered=len(group))
            except queue.Full:
                # backpressure stance: drop newest, count it; the ack is
                # withheld so the durable sender retransmits the group
                self.stats["dropped"] += len(group)
                self._hop.account(dropped=len(group), reason="queue_full")
                self._account_org_drop(group, "queue_full")

    def _dispatch_qos(self, frames: list[tuple[FrameHeader, bytes]],
                      lane: int = 0) -> None:
        """QoS dispatch: group one recv's frames by (org, msg_type) and
        admit each group through the fair-queuing tier.  The admission
        drain delivers to the decoder queues in DRR order; this thread
        only blocks when a tenant's HIGH queue is full (bounded wait =
        TCP backpressure).  Hop accounting: emitted here, delivered /
        dropped by the admission tier on the SAME receiver hop, so
        conservation holds with frames parked in admission counted as
        in_flight."""
        groups: dict[tuple[int, MessageType], list] = {}
        for header, payload in frames:
            self.stats["frames"] += 1
            self.stats["bytes"] += len(payload)
            key = (header.org_id, header.msg_type)
            group = groups.get(key)
            if group is None:
                group = groups[key] = []
            group.append((header, payload))
        self._hop.account(emitted=len(frames))
        enq_ns = time.monotonic_ns()
        admission = self._qos.admission
        for (org_id, msg_type), group in groups.items():
            if self._queues.get(msg_type) is None:
                self.stats["dropped"] += len(group)
                self._hop.account(dropped=len(group), reason="no_handler")
                self._account_org_drop(group, "no_handler")
                self._observe_seqs(group)
                continue
            verdict = admission.submit(
                org_id, priority_of(msg_type), msg_type, lane, group,
                enq_ns)
            if verdict != "admitted":
                # admission already accounted the hop ledger (and acked
                # quota sheds); mirror into stats + per-tenant split
                self.stats["dropped"] += len(group)
                self._account_org_drop(group, verdict)

    # -- TCP -----------------------------------------------------------------

    def _send_acks(self, sock, agents: set[int],
                   last_sent: dict[int, int]) -> None:
        """Write one ACK frame per agent seen on this connection (only
        when the contiguous watermark moved)."""
        for agent_id in agents:
            contig = self.seq_tracker.contiguous(agent_id)
            if contig is None or last_sent.get(agent_id) == contig:
                continue
            try:
                sock.sendall(encode_ack(agent_id, contig))
                last_sent[agent_id] = contig
                self.stats["acks_sent"] += 1
            except OSError:
                return  # peer gone; the read path will notice and close

    def start(self) -> "Receiver":
        recv = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                recv.stats["connections"] += 1
                sock = self.request
                with recv._handlers_lock:
                    if recv._stopping:
                        return
                    recv._handlers[threading.current_thread()] = sock
                try:
                    self._serve(sock)
                finally:
                    with recv._handlers_lock:
                        recv._handlers.pop(threading.current_thread(),
                                           None)

            def _serve(self, sock) -> None:
                if recv._chaos is not None:
                    recv._chaos.on_accept()
                with recv._handlers_lock:
                    lane = recv._lane_counter
                    recv._lane_counter += 1
                dec = StreamDecoder()
                # short read timeout: the ack writer needs to run even
                # when the peer is quiet; idle_deadline preserves the
                # old 60s dead-connection reap
                sock.settimeout(0.5)
                agents: set[int] = set()
                acks_sent: dict[int, int] = {}
                idle_deadline = time.monotonic() + 60.0
                while not recv._stopping:
                    try:
                        data = sock.recv(256 << 10)
                    except socket.timeout:
                        if time.monotonic() > idle_deadline:
                            return
                        if recv.ack_enabled:
                            recv._send_acks(sock, agents, acks_sent)
                        continue
                    except OSError:
                        return
                    if not data:
                        return
                    idle_deadline = time.monotonic() + 60.0
                    t0 = time.perf_counter_ns()
                    try:
                        frames = []
                        for h, p in dec.feed(data):
                            if h.msg_type == MessageType.SEQ_BASE:
                                # control frame: handled inline (and the
                                # agent gets acks from now on, so its
                                # _acked floor seeds before any data)
                                recv._handle_seq_base(h, p)
                                agents.add(h.agent_id)
                                continue
                            frames.append((h, p))
                            if h.seq is not None:
                                agents.add(h.agent_id)
                        if frames:
                            recv._dispatch_many(frames, lane)
                    except FrameDecodeError as e:
                        recv.stats["bad_frames"] += 1
                        recv._hop.account(emitted=1, dropped=1,
                                          reason="bad_frame")
                        log.warning("dropping connection: %s", e)
                        return
                    finally:
                        recv.stats["recv_ns"] += (
                            time.perf_counter_ns() - t0)
                    # ack EAGERLY (the moved-watermark check inside
                    # rate-limits): under fault injection a connection
                    # may only live a few ms, and an interval-gated ack
                    # never fires — the sender's retransmit window then
                    # never trims and every reconnect resends it all
                    if recv.ack_enabled:
                        recv._send_acks(sock, agents, acks_sent)

        # NOT beaten here: the first beat records the owning thread's
        # ident for stack snapshots, and that must be the serve loop
        hb = self.telemetry.heartbeat("receiver", interval_hint_s=0.5)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

            def service_actions(inner) -> None:
                # called by serve_forever every poll (~0.5s): the accept
                # loop's own liveness, with frame count as progress
                hb.beat(progress=recv.stats["frames"])

        self._tcp = Server((self.host, self.port), Handler)
        self.port = self._tcp.server_address[1]  # resolve port 0
        t = threading.Thread(target=self._tcp.serve_forever,
                             name="df-receiver-tcp", daemon=True)
        t.start()
        self._threads.append(t)
        if self._enable_udp:
            self._start_udp()
        return self

    # -- UDP (one frame per datagram) ---------------------------------------

    def _start_udp(self) -> None:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        s.settimeout(0.5)
        self._udp_sock = s

        def run() -> None:
            while self._udp_sock is not None:
                try:
                    data, _ = s.recvfrom(64 << 10)
                except socket.timeout:
                    continue
                except OSError:
                    return
                try:
                    header, payload, consumed = decode_frame(data)
                    if consumed:
                        if consumed < len(data):
                            # a datagram is ONE frame: trailing bytes are
                            # garbage — count them instead of silently
                            # ignoring, but keep the good frame
                            self.stats["bad_frames"] += 1
                            self.stats["udp_trailing_garbage"] += 1
                            self._hop.account(emitted=1, dropped=1,
                                              reason="udp_trailing_garbage")
                        if header.msg_type == MessageType.SEQ_BASE:
                            self._handle_seq_base(header, payload)
                        else:
                            self._dispatch(header, payload)
                    else:
                        # truncated datagram: header said more bytes than
                        # arrived
                        self.stats["bad_frames"] += 1
                        self._hop.account(emitted=1, dropped=1,
                                          reason="bad_frame")
                except FrameDecodeError:
                    self.stats["bad_frames"] += 1
                    self._hop.account(emitted=1, dropped=1,
                                      reason="bad_frame")

        t = threading.Thread(target=run, name="df-receiver-udp", daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        # order matters: no new handlers, kick live ones off their
        # sockets, then WAIT for them — only after that is it safe for
        # the caller to drain decoder queues and snapshot ack state
        # (a handler that dispatched after the drain would leave an
        # acked frame that never reached a table)
        with self._handlers_lock:
            self._stopping = True
            live = list(self._handlers.items())
        if self._tcp:
            self._tcp.shutdown()
            self._tcp.server_close()
            self._tcp = None
        for _, sock in live:
            try:
                sock.close()
            except OSError:
                pass
        for t, _ in live:
            t.join(timeout=2.0)
        if self._udp_sock:
            s, self._udp_sock = self._udp_sock, None
            s.close()
