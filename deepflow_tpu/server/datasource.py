"""Datasource rollups: derived 1m/1h/1d aggregates from 1s metric tables.

Reference analog: server/ingester/datasource (rollup management with
configurable aggregators per datasource). A periodic job aggregates
completed buckets from flow_metrics.*.1s upward using the query engine
itself, so the rollup algebra is exactly the algebra queries use —
Sum/Max/Min partials compose, which is what makes a rollup row
byte-identical to recomputing the same aggregate from raw rows.

Percentiles do NOT decompose, so they roll up as mergeable DDSketch
state (cluster/sketch.py) in a side column: PERCENTILE() over a long
range answers from the sketch within its relative-error bound (gamma)
instead of scanning raw rows.

query/datasource.py consumes `horizons()` to transparently swap a
query's table for the coarsest rollup tier that still answers exactly.
"""

from __future__ import annotations

import json
import logging
import threading
import time

from deepflow_tpu.query import engine as qengine
from deepflow_tpu.query import pool as qpool
from deepflow_tpu.store.db import Database

log = logging.getLogger("df.datasource")

# per-side universal resource tags carried through every rollup stage
from deepflow_tpu.store import schema as _schema

_SIDE_TAGS = [f"{n}_{s}" for s in ("0", "1")
              for n in _schema.SIDE_TAG_NAMES]


class RollupSpec:
    """One metric family's rollup recipe.

    tags     — group-by columns carried through unchanged
    aggs     — meter column -> aggregator name (Sum | Max | Min); the
               aggregator must be DECOMPOSABLE (partials merge by the
               same function), which is what keeps rollup == recompute
    sketches — sketch column -> source meter column: mergeable DDSketch
               JSON built from raw values at the first stage, merged
               bucket-wise at the later stages
    """

    def __init__(self, tags: list[str], aggs: dict[str, str],
                 sketches: dict[str, str] | None = None) -> None:
        for fn in aggs.values():
            if fn not in ("Sum", "Max", "Min"):
                raise ValueError(f"non-decomposable aggregator {fn!r}")
        self.tags = list(tags)
        self.aggs = dict(aggs)
        self.sketches = dict(sketches or {})


FAMILIES: dict[str, RollupSpec] = {
    "flow_metrics.network": RollupSpec(
        tags=["ip_src", "ip_dst", "server_port", "protocol", "direction",
              "agent_id", "host_id", "host", "pod_name", "pod_ns",
              "tpu_pod", "tpu_worker", "slice_id"] + _SIDE_TAGS,
        aggs={c: "Sum" for c in
              ["packet_tx", "packet_rx", "byte_tx", "byte_rx",
               "flow_count", "new_flow", "closed_flow", "rtt_sum",
               "rtt_count", "retrans", "syn_count", "synack_count"]}),
    "flow_metrics.application": RollupSpec(
        tags=["ip_src", "ip_dst", "server_port", "l7_protocol",
              "app_service", "agent_id", "host_id", "host", "pod_name",
              "pod_ns", "tpu_pod", "tpu_worker", "slice_id"] + _SIDE_TAGS,
        aggs={**{c: "Sum" for c in
                 ["request", "response", "rrt_sum", "rrt_count",
                  "error_client", "error_server", "timeout"]},
              "rrt_max": "Max"},
        sketches={"rrt_max_sketch": "rrt_max"}),
}


# rollup stages: (src interval suffix, dst suffix, bucket seconds)
_STAGES = [("1s", "1m", 60), ("1m", "1h", 3600),
           ("1h", "1d", 86400)]

# bucket width by interval suffix (1s tables hold raw-second rows)
BUCKET_S = {"1s": 1, "1m": 60, "1h": 3600, "1d": 86400}


class RollupJob:
    def __init__(self, db: Database, interval_s: float = 15.0,
                 lateness_s: int = 90) -> None:
        self.db = db
        self.interval_s = interval_s
        self.lateness_s = lateness_s  # wait for flow-timeout stragglers
        # per (family, stage): last fully-rolled bucket (epoch s);
        # restart-safe — initialized from the destination table's max(time)
        self._watermark: dict[tuple, int] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.stats = {"rollups": 0, "rows": 0, "sketch_rows": 0,
                      "compact_runs": 0}
        self._stats_lock = threading.Lock()  # families roll concurrently

    def start(self) -> "RollupJob":
        if self.running():
            return self
        self._stop.clear()  # restartable (HA leader churn)
        self._thread = threading.Thread(
            target=self._run, name="df-rollup", daemon=True)
        self._thread.start()
        return self

    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)
            if not self._thread.is_alive():
                self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.roll(now_s=int(time.time()))
            except Exception:
                log.exception("rollup failed")

    def _initial_watermark(self, dst, bucket: int) -> int:
        """Resume after restart: already-rolled buckets must not re-roll.
        The newest dst row marks its whole bucket as done."""
        best = 0
        for ch in dst.snapshot():
            t = ch.get("time")
            if t is not None and len(t):
                best = max(best, (int(t.max()) // bucket) * bucket + bucket)
        return best

    def horizons(self) -> dict[tuple[str, str], int]:
        """Per (family, interval-suffix) completeness horizon (epoch s,
        exclusive): every source row with time < horizon is represented
        in that tier. The 1s tier is always complete (it IS the source).
        Feeds transparent datasource selection (query/datasource.py) —
        a query whose time window closes under the horizon can answer
        from the rollup without missing late rows."""
        out: dict[tuple[str, str], int] = {}
        for family in FAMILIES:
            for src_sfx, dst_sfx, bucket in _STAGES:
                key = (family, dst_sfx)
                wm = self._watermark.get(key)
                if wm is None:
                    wm = self._initial_watermark(
                        self.db.table(f"{family}.{dst_sfx}"), bucket)
                    if wm:  # cache only a real resume point
                        self._watermark[key] = wm
                out[key] = wm
        return out

    def roll(self, now_s: int) -> int:
        """Run every rollup stage: complete buckets older than now-lateness.

        Families roll concurrently on the shared scan pool (they touch
        disjoint src/dst tables); the stages WITHIN a family stay serial
        because each feeds the next (1s -> 1m -> 1h -> 1d). Queries a
        stage runs inside a pool worker degrade to the serial engine
        path via the in_worker guard — no nested fan-out."""
        def _roll_family(item):
            family, spec = item
            n = 0
            for src_sfx, dst_sfx, bucket in _STAGES:
                n += self._roll_stage(
                    now_s, family, src_sfx, dst_sfx, bucket, spec)
            return n
        fams = list(FAMILIES.items())
        pool = qpool.get_pool()
        if pool is not None and len(fams) > 1:
            total = sum(pool.map(_roll_family, fams))
        else:
            total = sum(_roll_family(f) for f in fams)
        if total:
            self.stats["rollups"] += 1
            self.stats["rows"] += total
            self._compact_destinations()
        return total

    def _compact_destinations(self) -> None:
        """Rollup destinations accumulate one tiny flushed segment per
        completed bucket; fold them into sorted format-v2 runs so
        long-range queries over the coarse tiers scan a handful of runs
        instead of hundreds of slivers. No-op without tiered storage,
        and cheap when there is nothing to merge (single-run groups are
        skipped by the compaction planner)."""
        if getattr(self.db, "tier_store", None) is None:
            return
        for family in FAMILIES:
            for _src_sfx, dst_sfx, _bucket in _STAGES:
                try:
                    res = self.db.compact_tier(f"{family}.{dst_sfx}")
                except Exception:
                    log.exception("rollup compaction failed")
                    continue
                with self._stats_lock:
                    self.stats["compact_runs"] += res.get("runs_built", 0)

    def _sketch_map(self, src, spec: RollupSpec, sketch_col: str,
                    wm: int, horizon: int, bucket: int) -> dict:
        """(bucket_start, tag tuple) -> HistogramSketch for one stage's
        window. First stage (src has no sketch column): build from raw
        source values. Later stages: merge the src tier's JSON states —
        sketch merge is bucket-wise addition, so 1h == merging the 1m
        states == building from raw, modulo nothing (merge is exact on
        the sketch representation)."""
        from deepflow_tpu.cluster.sketch import HistogramSketch
        merging = sketch_col in src.columns
        val_col = sketch_col if merging else spec.sketches[sketch_col]
        sql_text = ("SELECT time, " + ", ".join(spec.tags) +
                    f", {val_col} FROM t "
                    f"WHERE time >= {wm} AND time < {horizon}")
        res = qengine.execute(src, sql_text)
        ntags = len(spec.tags)
        out: dict[tuple, HistogramSketch] = {}
        for row in res.values:
            key = ((int(row[0]) // bucket) * bucket,
                   tuple(row[1:1 + ntags]))
            sk = out.get(key)
            if sk is None:
                sk = out[key] = HistogramSketch()
            v = row[1 + ntags]
            if merging:
                if v:
                    try:
                        sk.merge(HistogramSketch.from_dict(json.loads(v)))
                    except (ValueError, TypeError):
                        log.warning("undecodable sketch state dropped")
            else:
                sk.add_many([v])
        return out

    def _roll_stage(self, now_s: int, family: str, src_sfx: str,
                    dst_sfx: str, bucket: int, spec: RollupSpec) -> int:
        src = self.db.table(f"{family}.{src_sfx}")
        dst = self.db.table(f"{family}.{dst_sfx}")
        if len(src) == 0:
            return 0
        # hold back: rows can arrive up to flow-timeout after their capture
        # bucket closes (flow_map flush semantics)
        horizon = ((now_s - self.lateness_s) // bucket) * bucket
        key = (family, dst_sfx)
        if key not in self._watermark:
            self._watermark[key] = self._initial_watermark(dst, bucket)
        wm = self._watermark[key]
        if horizon <= wm:
            return 0
        meters = list(spec.aggs)
        select = ", ".join(
            [f"time(time, {bucket}) AS tmin"] + spec.tags
            + [f"{fn}({c}) AS {c}" for c, fn in spec.aggs.items()])
        group = ", ".join([f"time(time, {bucket})"] + spec.tags)
        sql_text = (f"SELECT {select} FROM t "
                    f"WHERE time >= {wm} AND time < {horizon} "
                    f"GROUP BY {group}")
        res = qengine.execute(src, sql_text)
        n = 0
        if res.values:
            sketch_maps = {
                sc: self._sketch_map(src, spec, sc, wm, horizon, bucket)
                for sc in spec.sketches if sc in dst.columns}
            cols = {name: [] for name in res.columns}
            for row in res.values:
                for name, v in zip(res.columns, row):
                    cols[name].append(v)
            ntags = len(spec.tags)
            for sc, smap in sketch_maps.items():
                vals = []
                for row in res.values:
                    k = (int(row[0]), tuple(row[1:1 + ntags]))
                    sk = smap.get(k)
                    vals.append("" if sk is None
                                else json.dumps(sk.to_dict()))
                cols[sc] = vals
                with self._stats_lock:
                    self.stats["sketch_rows"] += len(vals)
            cols["time"] = [int(t) for t in cols.pop("tmin")]
            for c in meters:
                cols[c] = [int(v) for v in cols[c]]
            for c in list(cols):
                cspec = dst.columns[c]
                if cspec.kind == "enum":  # labels -> indices for append
                    cols[c] = [cspec.enum_of(v) for v in cols[c]]
            dst.append_columns(cols, n=len(res.values))
            n = len(res.values)
        self._watermark[key] = horizon
        return n
