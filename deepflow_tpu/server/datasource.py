"""Datasource rollups: derived 1m aggregates from 1s metric tables.

Reference analog: server/ingester/datasource (1m->1h->1d rollup management).
A periodic job aggregates completed minutes from flow_metrics.*.1s into
flow_metrics.*.1m using the query engine itself.
"""

from __future__ import annotations

import logging
import threading
import time

from deepflow_tpu.query import engine as qengine
from deepflow_tpu.query import sql as qsql
from deepflow_tpu.store.db import Database

log = logging.getLogger("df.datasource")

# per family: (tag columns, summed meter columns, max meter columns)
_FAMILIES = {
    "flow_metrics.network": (
        ["ip_src", "ip_dst", "server_port", "protocol", "direction",
         "agent_id", "host_id", "host", "pod_name", "pod_ns", "tpu_pod",
         "tpu_worker", "slice_id"],
        ["packet_tx", "packet_rx", "byte_tx", "byte_rx", "flow_count",
         "new_flow", "closed_flow", "rtt_sum", "rtt_count", "retrans",
         "syn_count", "synack_count"],
        []),
    "flow_metrics.application": (
        ["ip_src", "ip_dst", "server_port", "l7_protocol", "app_service",
         "agent_id", "host_id", "host", "pod_name", "pod_ns", "tpu_pod",
         "tpu_worker", "slice_id"],
        ["request", "response", "rrt_sum", "rrt_count", "error_client",
         "error_server", "timeout"],
        ["rrt_max"]),
}


class RollupJob:
    def __init__(self, db: Database, interval_s: float = 15.0,
                 lateness_s: int = 90) -> None:
        self.db = db
        self.interval_s = interval_s
        self.lateness_s = lateness_s  # wait for flow-timeout stragglers
        # per family: last fully-rolled minute (epoch s); restart-safe —
        # initialized from the destination table's max(time)
        self._watermark: dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.stats = {"rollups": 0, "rows": 0}

    def start(self) -> "RollupJob":
        self._thread = threading.Thread(
            target=self._run, name="df-rollup", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.roll(now_s=int(time.time()))
            except Exception:
                log.exception("rollup failed")

    def _initial_watermark(self, dst) -> int:
        """Resume after restart: already-rolled minutes must not re-roll."""
        best = 0
        for ch in dst.snapshot():
            t = ch.get("time")
            if t is not None and len(t):
                best = max(best, int(t.max()) + 60)
        return best

    def roll(self, now_s: int) -> int:
        """Aggregate every complete minute older than now - lateness."""
        total = 0
        # hold back: 1s rows can arrive up to flow-timeout after their
        # capture minute closes (flow_map flush semantics)
        horizon = ((now_s - self.lateness_s) // 60) * 60
        for family, (tags, sums, maxes) in _FAMILIES.items():
            src = self.db.table(f"{family}.1s")
            dst = self.db.table(f"{family}.1m")
            if len(src) == 0:
                continue
            if family not in self._watermark:
                self._watermark[family] = self._initial_watermark(dst)
            wm = self._watermark[family]
            if horizon <= wm:
                continue
            select = ", ".join(
                ["time(time, 60) AS tmin"] + tags
                + [f"Sum({c}) AS {c}" for c in sums]
                + [f"Max({c}) AS {c}" for c in maxes])
            group = ", ".join(["time(time, 60)"] + tags)
            sql_text = (f"SELECT {select} FROM t "
                        f"WHERE time >= {wm} AND time < {horizon} "
                        f"GROUP BY {group}")
            res = qengine.execute(src, sql_text)
            if res.values:
                cols = {name: [] for name in res.columns}
                for row in res.values:
                    for name, v in zip(res.columns, row):
                        cols[name].append(v)
                cols["time"] = [int(t) for t in cols.pop("tmin")]
                for c in sums + maxes:
                    cols[c] = [int(v) for v in cols[c]]
                for c in list(cols):
                    spec = dst.columns[c]
                    if spec.kind == "enum":  # labels -> indices for append
                        cols[c] = [spec.enum_of(v) for v in cols[c]]
                dst.append_columns(cols, n=len(res.values))
                total += len(res.values)
            self._watermark[family] = horizon
        if total:
            self.stats["rollups"] += 1
            self.stats["rows"] += total
        return total
