"""Datasource rollups: derived 1m aggregates from 1s metric tables.

Reference analog: server/ingester/datasource (1m->1h->1d rollup management).
A periodic job aggregates completed minutes from flow_metrics.*.1s into
flow_metrics.*.1m using the query engine itself.
"""

from __future__ import annotations

import logging
import threading
import time

from deepflow_tpu.query import engine as qengine
from deepflow_tpu.query import sql as qsql
from deepflow_tpu.store.db import Database

log = logging.getLogger("df.datasource")

# per family: (tag columns, summed meter columns, max meter columns)
# per-side universal resource tags carried through every rollup stage
from deepflow_tpu.store import schema as _schema

_SIDE_TAGS = [f"{n}_{s}" for s in ("0", "1")
              for n in _schema.SIDE_TAG_NAMES]

_FAMILIES = {
    "flow_metrics.network": (
        ["ip_src", "ip_dst", "server_port", "protocol", "direction",
         "agent_id", "host_id", "host", "pod_name", "pod_ns", "tpu_pod",
         "tpu_worker", "slice_id"] + _SIDE_TAGS,
        ["packet_tx", "packet_rx", "byte_tx", "byte_rx", "flow_count",
         "new_flow", "closed_flow", "rtt_sum", "rtt_count", "retrans",
         "syn_count", "synack_count"],
        []),
    "flow_metrics.application": (
        ["ip_src", "ip_dst", "server_port", "l7_protocol", "app_service",
         "agent_id", "host_id", "host", "pod_name", "pod_ns", "tpu_pod",
         "tpu_worker", "slice_id"] + _SIDE_TAGS,
        ["request", "response", "rrt_sum", "rrt_count", "error_client",
         "error_server", "timeout"],
        ["rrt_max"]),
}


# rollup stages: (src interval suffix, dst suffix, bucket seconds)
_STAGES = [("1s", "1m", 60), ("1m", "1h", 3600),
           ("1h", "1d", 86400)]


class RollupJob:
    def __init__(self, db: Database, interval_s: float = 15.0,
                 lateness_s: int = 90) -> None:
        self.db = db
        self.interval_s = interval_s
        self.lateness_s = lateness_s  # wait for flow-timeout stragglers
        # per (family, stage): last fully-rolled bucket (epoch s);
        # restart-safe — initialized from the destination table's max(time)
        self._watermark: dict[tuple, int] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.stats = {"rollups": 0, "rows": 0}

    def start(self) -> "RollupJob":
        if self.running():
            return self
        self._stop.clear()  # restartable (HA leader churn)
        self._thread = threading.Thread(
            target=self._run, name="df-rollup", daemon=True)
        self._thread.start()
        return self

    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)
            if not self._thread.is_alive():
                self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.roll(now_s=int(time.time()))
            except Exception:
                log.exception("rollup failed")

    def _initial_watermark(self, dst, bucket: int) -> int:
        """Resume after restart: already-rolled buckets must not re-roll.
        The newest dst row marks its whole bucket as done."""
        best = 0
        for ch in dst.snapshot():
            t = ch.get("time")
            if t is not None and len(t):
                best = max(best, (int(t.max()) // bucket) * bucket + bucket)
        return best

    def roll(self, now_s: int) -> int:
        """Run every rollup stage: complete buckets older than now-lateness."""
        total = 0
        for family, (tags, sums, maxes) in _FAMILIES.items():
            for src_sfx, dst_sfx, bucket in _STAGES:
                total += self._roll_stage(
                    now_s, family, src_sfx, dst_sfx, bucket,
                    tags, sums, maxes)
        if total:
            self.stats["rollups"] += 1
            self.stats["rows"] += total
        return total

    def _roll_stage(self, now_s: int, family: str, src_sfx: str,
                    dst_sfx: str, bucket: int, tags, sums, maxes) -> int:
        src = self.db.table(f"{family}.{src_sfx}")
        dst = self.db.table(f"{family}.{dst_sfx}")
        if len(src) == 0:
            return 0
        # hold back: rows can arrive up to flow-timeout after their capture
        # bucket closes (flow_map flush semantics)
        horizon = ((now_s - self.lateness_s) // bucket) * bucket
        key = (family, dst_sfx)
        if key not in self._watermark:
            self._watermark[key] = self._initial_watermark(dst, bucket)
        wm = self._watermark[key]
        if horizon <= wm:
            return 0
        select = ", ".join(
            [f"time(time, {bucket}) AS tmin"] + tags
            + [f"Sum({c}) AS {c}" for c in sums]
            + [f"Max({c}) AS {c}" for c in maxes])
        group = ", ".join([f"time(time, {bucket})"] + tags)
        sql_text = (f"SELECT {select} FROM t "
                    f"WHERE time >= {wm} AND time < {horizon} "
                    f"GROUP BY {group}")
        res = qengine.execute(src, sql_text)
        n = 0
        if res.values:
            cols = {name: [] for name in res.columns}
            for row in res.values:
                for name, v in zip(res.columns, row):
                    cols[name].append(v)
            cols["time"] = [int(t) for t in cols.pop("tmin")]
            for c in sums + maxes:
                cols[c] = [int(v) for v in cols[c]]
            for c in list(cols):
                spec = dst.columns[c]
                if spec.kind == "enum":  # labels -> indices for append
                    cols[c] = [spec.enum_of(v) for v in cols[c]]
            dst.append_columns(cols, n=len(res.values))
            n = len(res.values)
        self._watermark[key] = horizon
        return n
