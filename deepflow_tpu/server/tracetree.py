"""Ingest-time trace-tree precompute.

Reference analog: server/ingester/flow_log/dbwriter/tracetree_writer.go:74
(the aggregation window keyed by trace search-id) +
server/libs/tracetree/tracetree.go:47 (the encoded per-trace node list).

Redesign: FlowLogDecoder feeds every l7 row that carries a trace_id into a
TraceTreeBuilder. Spans accumulate in memory per trace; once a trace has
been idle for `flush_after_s` its compact span list is written as ONE row
to flow_log.trace_tree (append-only: a late straggler batch simply
produces a second row for the same trace, merged at read time). Queries
touch only that trace's rows; service-path search scans the per-trace
table, never per-span l7_flow_log.
"""

from __future__ import annotations

import json
import logging
import threading
import time

log = logging.getLogger("df.tracetree")

# span fields persisted into the encoded tree (a projection of the l7 row:
# enough to rebuild the tree + stats without going back to l7_flow_log)
SPAN_FIELDS = ("span_id", "parent_span_id", "name", "service",
               "l7_protocol", "start_ns", "end_ns", "status",
               "response_code", "ip_src", "ip_dst", "flow_id",
               "x_request_id")


def span_from_l7(row: dict) -> dict:
    """Project one decoded l7 row dict into the persisted span shape."""
    name = row.get("endpoint") or row.get("request_resource") or \
        row.get("request_type") or ""
    start = int(row.get("time", 0))
    return {
        "span_id": row.get("span_id")
        or f"flow-{row.get('flow_id', 0)}-{row.get('request_id', 0)}",
        "parent_span_id": row.get("parent_span_id", ""),
        "name": f"{row.get('request_type', '')} {name}".strip(),
        "service": row.get("app_service") or row.get("service_1")
        or row.get("host", ""),
        "l7_protocol": row.get("l7_protocol", ""),
        "start_ns": start,
        "end_ns": start + int(row.get("response_duration", 0)),
        "status": row.get("response_status", "unknown"),
        "response_code": int(row.get("response_code", 0)),
        "ip_src": row.get("ip_src", ""),
        "ip_dst": row.get("ip_dst", ""),
        "flow_id": int(row.get("flow_id", 0)),
        "x_request_id": row.get("x_request_id", ""),
    }


def service_path(spans: list[dict]) -> list[str]:
    """DFS-ordered unique service sequence (the searchable path)."""
    by_id = {s["span_id"]: s for s in spans if s["span_id"]}
    children: dict[str, list] = {}
    roots = []
    for s in sorted(spans, key=lambda x: x["start_ns"]):
        p = s.get("parent_span_id", "")
        if p and p in by_id and by_id[p] is not s:
            children.setdefault(p, []).append(s)
        else:
            roots.append(s)
    path: list[str] = []

    def walk(s):
        svc = s.get("service", "")
        if svc and (not path or path[-1] != svc):
            path.append(svc)
        for c in children.get(s["span_id"], []):
            walk(c)

    for r in roots:
        walk(r)
    return path


class TraceTreeBuilder:
    """Accumulates spans per trace_id; flushes idle traces to the
    flow_log.trace_tree table."""

    def __init__(self, db, flush_after_s: float = 4.0,
                 max_spans_per_trace: int = 100_000) -> None:
        self.db = db
        self.flush_after_s = flush_after_s
        self.max_spans_per_trace = max_spans_per_trace
        self._lock = threading.Lock()
        self._pending: dict[str, list[dict]] = {}
        self._last_seen: dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.stats = {"spans": 0, "traces_flushed": 0, "rows": 0,
                      "dropped_spans": 0}

    # -- ingest side ----------------------------------------------------------

    def add_span(self, trace_id: str, span: dict) -> None:
        if not trace_id:
            return
        with self._lock:
            lst = self._pending.setdefault(trace_id, [])
            if len(lst) >= self.max_spans_per_trace:
                self.stats["dropped_spans"] += 1
                return
            lst.append(span)
            self._last_seen[trace_id] = time.monotonic()
            self.stats["spans"] += 1

    def pending_spans(self, trace_id: str) -> list[dict]:
        """Spans accumulated but not yet flushed (read-time merge)."""
        with self._lock:
            return list(self._pending.get(trace_id, ()))

    def pending_summaries(self) -> list[dict]:
        """Search-shape entries for traces still buffering (so search
        sees in-flight traces without forcing a premature flush)."""
        with self._lock:
            items = [(tid, list(spans))
                     for tid, spans in self._pending.items()]
        out = []
        for tid, spans in items:
            if not spans:
                continue
            start = min(s["start_ns"] for s in spans)
            end = max(s["end_ns"] for s in spans)
            path = service_path(spans)
            out.append({
                "trace_id": tid, "time": start,
                "duration_ns": max(0, end - start),
                "span_count": len(spans),
                "root_service": path[0] if path else "",
                "services": path,
            })
        return out

    # -- flush side -----------------------------------------------------------

    def flush_idle(self, now: float | None = None) -> int:
        now = time.monotonic() if now is None else now
        with self._lock:
            ripe = [tid for tid, seen in self._last_seen.items()
                    if now - seen >= self.flush_after_s]
            batches = {tid: self._pending.pop(tid) for tid in ripe}
            for tid in ripe:
                del self._last_seen[tid]
        return self._write(batches)

    def flush_all(self) -> int:
        with self._lock:
            batches = self._pending
            self._pending = {}
            self._last_seen.clear()
        return self._write(batches)

    def _write(self, batches: dict[str, list[dict]]) -> int:
        rows = []
        for tid, spans in batches.items():
            if not spans:
                continue
            start = min(s["start_ns"] for s in spans)
            end = max(s["end_ns"] for s in spans)
            path = service_path(spans)
            rows.append({
                "time": start,
                "trace_id": tid,
                "span_count": len(spans),
                "duration_ns": max(0, end - start),
                "root_service": path[0] if path else "",
                "services": json.dumps(path),
                "tree": json.dumps(spans, separators=(",", ":")),
            })
        if rows:
            self.db.table("flow_log.trace_tree").append_rows(rows)
            self.stats["traces_flushed"] += len(rows)
            self.stats["rows"] += len(rows)
        return len(rows)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "TraceTreeBuilder":
        self._thread = threading.Thread(
            target=self._run, name="df-tracetree", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=3.0)
        self.flush_all()

    def _run(self) -> None:
        interval = max(0.5, self.flush_after_s / 4)
        while not self._stop.wait(interval):
            try:
                self.flush_idle()
            except Exception:
                log.exception("trace-tree flush failed")


def search(table, service_path_query: list[str] | None = None,
           root_service: str | None = None,
           time_from_ns: int = 0, time_to_ns: int = 0,
           min_duration_ns: int = 0, limit: int = 50,
           pending: list[dict] | None = None) -> list[dict]:
    """Service-path search over precomputed trace_tree rows.

    `service_path_query` matches traces whose DFS service path contains
    the given services as a contiguous subsequence (e.g. ['cart', 'db']
    finds every trace where cart called db).
    """
    import numpy as np

    want = list(service_path_query or [])
    hits: dict[str, dict] = {}
    for ch in table.snapshot():
        if not ch:
            continue
        mask = np.ones(len(ch["time"]), dtype=bool)
        if time_from_ns:
            mask &= ch["time"] >= time_from_ns
        if time_to_ns:
            mask &= ch["time"] < time_to_ns
        if min_duration_ns:
            mask &= ch["duration_ns"] >= min_duration_ns
        if root_service is not None:
            code = table.dicts["root_service"].lookup(root_service)
            mask &= (ch["root_service"] == (code if code is not None
                                            else 0xFFFFFFFF))
        for i in np.flatnonzero(mask).tolist():
            tid = table.dicts["trace_id"].decode(int(ch["trace_id"][i]))
            path = json.loads(
                table.dicts["services"].decode(int(ch["services"][i])))
            if want and not _contains_subseq(path, want):
                continue
            prev = hits.get(tid)
            entry = {
                "trace_id": tid,
                "time": int(ch["time"][i]),
                "duration_ns": int(ch["duration_ns"][i]),
                "span_count": int(ch["span_count"][i]),
                "root_service": table.dicts["root_service"].decode(
                    int(ch["root_service"][i])),
                "services": path,
            }
            if prev is None:
                hits[tid] = entry
            else:  # merge straggler rows of the same trace
                prev["span_count"] += entry["span_count"]
                lo = min(prev["time"], entry["time"])
                hi = max(prev["time"] + prev["duration_ns"],
                         entry["time"] + entry["duration_ns"])
                prev["time"], prev["duration_ns"] = lo, hi - lo
                for svc in entry["services"]:
                    if svc not in prev["services"]:
                        prev["services"].append(svc)
    for entry in pending or ():
        if time_from_ns and entry["time"] < time_from_ns:
            continue
        if time_to_ns and entry["time"] >= time_to_ns:
            continue
        if min_duration_ns and entry["duration_ns"] < min_duration_ns:
            continue
        if root_service is not None and \
                entry["root_service"] != root_service:
            continue
        if want and not _contains_subseq(entry["services"], want):
            continue
        prev = hits.get(entry["trace_id"])
        if prev is None:
            hits[entry["trace_id"]] = entry
        else:
            prev["span_count"] += entry["span_count"]
            lo = min(prev["time"], entry["time"])
            hi = max(prev["time"] + prev["duration_ns"],
                     entry["time"] + entry["duration_ns"])
            prev["time"], prev["duration_ns"] = lo, hi - lo
            for svc in entry["services"]:
                if svc not in prev["services"]:
                    prev["services"].append(svc)
    out = sorted(hits.values(), key=lambda h: -h["time"])
    return out[:limit]


def _contains_subseq(path: list[str], want: list[str]) -> bool:
    n, m = len(path), len(want)
    if m == 0:
        return True
    return any(path[i:i + m] == want for i in range(n - m + 1))
