"""Per-message-type decoders: pb payloads -> tag-injected store rows.

Reference analog: server/ingester/*/decoder (e.g. profile/decoder/decoder.go
:190 handleProfileData, flow_log/decoder/decoder.go:151 Run). Each decoder
owns one receiver queue, runs on its own thread, and writes columnar batches.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import struct
import threading
import time

import numpy as np

from deepflow_tpu.codec import FrameHeader, MessageType
from deepflow_tpu.native import (
    ArenaStrings, IP_FALLBACK, IP_SRC_EMPTY, IP_DST_EMPTY)
from deepflow_tpu.proto import pb
from deepflow_tpu.store.db import Database
from deepflow_tpu.store.schema import (
    L4_PROTOS, L7_PROTOS, PROFILE_EVENT_TYPES, RESPONSE_STATUS,
    SIDE_RESOLVE_NAMES, TPU_SPAN_KINDS, CLOSE_TYPES)
from deepflow_tpu.server.platform_info import PlatformInfoTable

log = logging.getLogger("df.decoder")


class DedupWindow:
    """Per-agent exactly-once guard: a contiguity-advancing ``floor``
    plus a bounded park set of decoded seqs above it.

    The at-least-once transport retransmits frames the server may
    already hold (unacked window replay after a reconnect, spool replay
    racing an in-flight ack); this window is what turns at-least-once
    frames into exactly-once rows.  Every seq at or below an agent's
    floor is a dup; seqs above it park in a per-agent set and are
    absorbed into the floor as the run becomes contiguous — so under
    normal (dense) decode traffic the floor tracks the stream and the
    park set holds only out-of-order residue.  Unlike the shared LRU
    this replaces, one agent's traffic can never evict another agent's
    still-live entries and reopen a dup hole.

    Floors move three ways: seeded from persisted ack state on server
    restart, advanced by ``advance_floor`` when a SEQ_BASE announcement
    declares a gap permanently dead (safe: acked => decoded, so nothing
    below the announced base can still be in a decoder queue), and
    advanced by ``seen`` contiguity.  If a park set still outgrows
    ``capacity`` (an un-announced permanent gap), the floor jumps to
    the oldest parked seq — bounded memory over perfect dup detection
    for seqs that old, same liveness-over-completeness stance as
    SeqAckTracker.MAX_OOS.

    One window is shared by ALL decoders (seq space is per-agent, not
    per-type) and workers, hence the lock."""

    def __init__(self, capacity: int = 65536,
                 floors: dict[int, int] | None = None) -> None:
        self.capacity = capacity
        self._lock = threading.Lock()
        # agent_id -> [floor, set of parked seqs > floor]
        self._state: dict[int, list] = {
            int(a): [int(f), set()] for a, f in (floors or {}).items()}
        self.stats = {"dups": 0, "tracked": 0, "floor_jumps": 0}

    def seen(self, agent_id: int, seq: int) -> bool:
        """Mark (agent, seq); True if it was already marked (a dup)."""
        with self._lock:
            st = self._state.get(agent_id)
            if st is None:
                st = self._state[agent_id] = [0, set()]
            floor, parked = st
            if seq <= floor or seq in parked:
                self.stats["dups"] += 1
                return True
            parked.add(seq)
            self.stats["tracked"] += 1
            if seq == floor + 1:
                while floor + 1 in parked:
                    floor += 1
                    parked.discard(floor)
                st[0] = floor
            elif len(parked) > self.capacity:
                # un-announced permanent gap: jump to the oldest parked
                # seq and absorb the contiguous run above it
                floor = min(parked)
                parked.discard(floor)
                while floor + 1 in parked:
                    floor += 1
                    parked.discard(floor)
                st[0] = floor
                self.stats["floor_jumps"] += 1
            return False

    def advance_floor(self, agent_id: int, floor: int) -> None:
        """Forward-only floor jump (SEQ_BASE / restored ack state)."""
        with self._lock:
            st = self._state.get(agent_id)
            if st is None:
                self._state[agent_id] = [floor, set()]
                return
            if floor <= st[0]:
                return
            parked = st[1]
            parked.difference_update({s for s in parked if s <= floor})
            while floor + 1 in parked:
                floor += 1
                parked.discard(floor)
            st[0] = floor


class Decoder:
    """Base: drain one queue, decode, write. Subclasses set MSG_TYPE."""

    MSG_TYPE: MessageType

    WORKERS = 1  # ingest parallelism (reference: per-type decoder queues
    # with N workers, flow_metrics.go:55-61). FlowLogDecoder overrides
    # via DF_INGEST_WORKERS: its native columnar parse (pbcols.cpp)
    # releases the GIL, so extra workers scale across cores — unlike the
    # python-object decode this comment used to caveat.
    # Row ORDER across workers is not guaranteed.

    def __init__(self, q: queue.Queue, db: Database,
                 platform: PlatformInfoTable, exporters=None,
                 pod_index=None, gpid_table=None,
                 workers: int | None = None, resources=None,
                 trace_trees=None, telemetry=None, dedup=None,
                 seq_tracker=None, ring=None, durability=None,
                 qos_sampler=None) -> None:
        # q: one Queue, or a LIST of lane queues (receiver connection
        # affinity — see Receiver.register(lanes=)). With N lanes and N
        # workers each worker owns one lane exclusively, so one hot
        # agent's connection can never serialize its siblings.
        self.queues: list[queue.Queue] = (
            list(q) if isinstance(q, (list, tuple)) else [q])
        self.q = self.queues[0]  # single-queue contract for tests/tools
        self.db = db
        self.platform = platform
        self.exporters = exporters
        self.pod_index = pod_index  # K8s genesis IP->pod (optional)
        self.resources = resources  # ResourceIndex: ip -> universal tags
        self.trace_trees = trace_trees  # TraceTreeBuilder (optional)
        self.gpid_table = gpid_table  # controller GpidAllocator (optional)
        self.dedup = dedup  # shared DedupWindow (optional): retransmit guard
        # receiver's SeqAckTracker (optional): seqs are observed HERE,
        # after decode+write, so an ack implies store presence — a hard
        # server crash can only lose frames the agent will retransmit
        self.seq_tracker = seq_tracker
        # DurabilityGate (optional, storage mode): seqs are PARKED here
        # after decode+write instead of observed — the flusher releases
        # them into seq_tracker only once the rows' tier commit landed,
        # so an ack then implies the rows survive SIGKILL
        self.durability = durability
        # replication (cluster/hashring.py): zero-arg callable returning
        # the current HashRing (or None). When set, every ingested row
        # is tagged with its agent's ring-primary owner_shard and the
        # ring epoch — the coordinates the query-time claim filter
        # dedups replica copies by.
        self.ring = ring
        # qos/sampling.AdaptiveSampler (optional): tail-aware head
        # sampling of bulk flow/L7 records when the frame's tenant is
        # under pressure (only FlowLogDecoder consults it)
        self.qos_sampler = qos_sampler
        self.workers = workers if workers is not None else self.WORKERS
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._stats_lock = threading.Lock()
        # handle_ns: total wall time inside handle(); append_ns: the part
        # spent in store appends (handle_ns - append_ns = pure decode).
        # Exposed so the ingest bench can localize regressions per stage.
        self.stats = {"batches": 0, "rows": 0, "errors": 0, "dups": 0,
                      "handle_ns": 0, "append_ns": 0}
        if telemetry is None:
            from deepflow_tpu.telemetry import Telemetry
            telemetry = Telemetry("server", enabled=False)
        self.telemetry = telemetry
        # hops are created in start(): MSG_TYPE may be assigned after
        # construction (FlowLogDecoder serves two message types)
        self._hop = None
        self._tw_hop = None

    def start(self) -> "Decoder":
        self._hop = self.telemetry.hop(f"decoder.{self.MSG_TYPE.name}")
        self._tw_hop = self.telemetry.hop("table_write")
        for i in range(max(1, self.workers)):
            t = threading.Thread(
                target=self._run, args=(i,),
                name=f"df-decoder-{self.MSG_TYPE.name}-{i}", daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads = []
        if self._hop is None:
            return  # never started: nothing accepted, nothing to drain
        # drain what's still queued (every lane): frames here were
        # ACCEPTED (and, on the durable path, acked) — exiting with a
        # non-empty queue would lose them on every restart even though
        # the agent was told not to retransmit
        drained = []
        for lane_q in self.queues:
            while True:
                try:
                    drained.extend(self._unwrap(lane_q.get_nowait()))
                except queue.Empty:
                    break
        if drained:
            self._handle_items(drained)

    def _handle_items(self, items: list) -> None:
        """Decode+write a list of (header, payload); shared by the worker
        loop and the shutdown drain."""
        batches = rows = errors = dups = 0
        t0 = time.perf_counter_ns()
        for header, payload in items:
            if (self.dedup is not None and header.seq is not None
                    and self.dedup.seen(header.agent_id, header.seq)):
                dups += 1
                continue
            try:
                rows += self.handle(header, payload)
                batches += 1
            except Exception:
                errors += 1
                log.exception("decode error (%s)", self.MSG_TYPE.name)
        dt = time.perf_counter_ns() - t0
        if self.durability is not None:
            # storage mode: park AFTER the decode/write pass; the
            # flusher observes into the tracker post-commit (dups and
            # decode errors park too — a retransmit meets the same fate)
            for header, _ in items:
                if header.seq is not None:
                    self.durability.add(header.agent_id, header.seq)
        elif self.seq_tracker is not None:
            # observed AFTER the decode/write pass: dups and decode
            # errors count too (a retransmit would meet the same fate)
            for header, _ in items:
                if header.seq is not None:
                    self.seq_tracker.observe(header.agent_id, header.seq)
        if dups:
            self._hop.account(dropped=dups, reason="dup")
        self._hop.account(delivered=batches, dropped=errors,
                          reason="decode_error" if errors else "")
        with self._stats_lock:
            self.stats["batches"] += batches
            self.stats["rows"] += rows
            self.stats["errors"] += errors
            self.stats["dups"] += dups
            self.stats["handle_ns"] += dt

    DRAIN_FRAMES = 64  # max frames one worker consumes per wakeup

    def _unwrap(self, item) -> list:
        """Accept both the receiver's ``(enqueue_ns, frames)`` shape and a
        bare frame list (tests feed decoder queues directly); account the
        dequeue on the ledger + queue-wait histogram."""
        if isinstance(item, tuple):
            enq_ns, frames = item
            self._hop.account(emitted=len(frames),
                              wait_ns=time.monotonic_ns() - enq_ns)
        else:
            frames = item
            self._hop.account(emitted=len(frames))
        return frames

    def _run(self, worker_idx: int = 0) -> None:
        hb = self.telemetry.heartbeat(
            f"decoder.{self.MSG_TYPE.name}.{worker_idx}")
        # lane affinity: worker i owns queue i (mod lanes). With
        # lanes == workers each lane has exactly one consumer, so frame
        # order within a connection is preserved end to end.
        lane_q = self.queues[worker_idx % len(self.queues)]
        handled = 0
        while not self._stop.is_set():
            hb.beat(progress=handled)
            try:
                items = self._unwrap(lane_q.get(timeout=0.2))
            except queue.Empty:
                continue
            # greedy drain: the receiver enqueues LISTS of frames (one per
            # recv()), and each wakeup additionally drains whatever else is
            # already queued — bounded so one worker doesn't starve its
            # siblings under WORKERS > 1
            while len(items) < self.DRAIN_FRAMES:
                try:
                    items = items + self._unwrap(lane_q.get_nowait())
                except queue.Empty:
                    break
            handled += len(items)
            self._handle_items(items)

    def handle(self, header: FrameHeader, payload: bytes) -> int:
        raise NotImplementedError

    def _agent_tags(self, header: FrameHeader) -> dict:
        """Universal tags for this frame's agent, plus — when a ring is
        active — the replication coordinates (owner_shard, ring_epoch).
        Server-local sinks bypass this and stay ring_epoch 0: their rows
        exist in one copy and must be reported unconditionally."""
        tags = self.platform.tags_for(header.agent_id)
        ring = self.ring() if self.ring is not None else None
        if ring is not None:
            owners = ring.owners(header.agent_id)
            if owners:
                tags = dict(tags)
                tags["owner_shard"] = owners[0]
                tags["ring_epoch"] = ring.epoch
        return tags

    def _clock_offset(self, header: FrameHeader) -> int:
        """NTP normalization: ns to add to this agent's absolute
        timestamps (sub-ms offsets are measurement noise, not skew)."""
        off = self.platform.offset_for(header.agent_id)
        return off if abs(off) >= 1_000_000 else 0

    def write(self, table_name: str, rows: list[dict]) -> None:
        """Append + feed the re-export pipeline (reference: exporters)."""
        t0 = time.perf_counter_ns()
        self.db.table(table_name).append_rows(rows)
        dt = time.perf_counter_ns() - t0
        if self._tw_hop is not None:
            self._tw_hop.account(emitted=len(rows), delivered=len(rows),
                                 wait_ns=dt)
        with self._stats_lock:
            self.stats["append_ns"] += dt
        if self.exporters is not None and rows:
            self.exporters.feed(table_name, rows)

    def write_columns(self, table_name: str, cols: dict[str, list],
                      n: int) -> None:
        """Columnar append (the hot-path shape: one list per column, no
        per-row dicts). Row dicts are materialized for the re-export
        pipeline ONLY if an exporter actually wants this table."""
        t0 = time.perf_counter_ns()
        self.db.table(table_name).append_columns(cols, n)
        dt = time.perf_counter_ns() - t0
        if self._tw_hop is not None:
            self._tw_hop.account(emitted=n, delivered=n, wait_ns=dt)
        with self._stats_lock:
            self.stats["append_ns"] += dt
        if (self.exporters is not None and n
                and self.exporters.wants(table_name)):
            names = list(cols)
            # ndarray -> tolist(): exported cells must be PYTHON numbers
            # (np scalars would json-serialize via default=str as strings,
            # silently changing the export wire format)
            expanded = [v.tolist()
                        if isinstance(v, (np.ndarray, ArenaStrings))
                        else v if isinstance(v, list) else [v] * n
                        for v in cols.values()]
            self.exporters.feed(
                table_name,
                [dict(zip(names, vals)) for vals in zip(*expanded)])


class ProfileDecoder(Decoder):
    """ProfileBatch -> profile.in_process_profile."""

    MSG_TYPE = MessageType.PROFILE

    def handle(self, header: FrameHeader, payload: bytes) -> int:
        batch = pb.ProfileBatch.FromString(payload)
        tags = self._agent_tags(header)
        off = self._clock_offset(header)
        rows = []
        for p in batch.profiles:
            rows.append({
                "time": p.timestamp_ns + off,
                "app_service": p.app_service or p.process_name,
                "process_name": p.process_name,
                "event_type": int(p.event_type),
                "profiler": p.profiler,
                "pid": p.pid,
                "tid": p.tid,
                "thread_name": p.thread_name,
                "stack": p.stack.decode("utf-8", "replace"),
                "value": p.value,
                "count": p.count,
                **tags,
            })
        self.write("profile.in_process_profile", rows)
        return len(rows)


class TpuSpanDecoder(Decoder):
    """TpuSpanBatch -> profile.tpu_hlo_span.

    Hot path: native columnar decode (native/ingest.cpp
    df_decode_span_cols) — span and memory-sample fields land in numpy
    arrays with the GIL released, string cells stay (arena, off, len)
    until the dictionary interns them in C++. Malformed/overflow batches
    ride the protobuf fallback; both paths must write identical rows
    (golden parity test)."""

    MSG_TYPE = MessageType.TPU_SPAN

    def __init__(self, *a, **kw) -> None:
        super().__init__(*a, **kw)
        self._tl = threading.local()  # per-worker native decode buffers

    def _fast_decoder(self):
        dec = getattr(self._tl, "spancols", False)
        if dec is False:
            try:
                from deepflow_tpu.native import SpanColumnDecoder
                dec = SpanColumnDecoder()
            except Exception:
                dec = None
            self._tl.spancols = dec
        return dec

    def _handle_cols(self, header: FrameHeader, n: int, cols: dict,
                     n_mem: int, arena) -> int:
        tags = self._agent_tags(header)
        off = self._clock_offset(header)

        def lazy(name: str):
            lens = cols[f"{name}_len"]
            if not lens.any():
                return ""
            return ArenaStrings(arena, cols[f"{name}_off"], lens)

        def shifted(t: np.ndarray) -> np.ndarray:
            if not off:
                return t
            return (t.astype(np.int64) + off).astype(np.uint64)

        if n:
            pname = lazy("process_name")
            out = {
                "time": shifted(cols["start_ns"]),
                "duration_ns": cols["duration_ns"],
                "device_id": cols["device_id"],
                "chip_id": cols["chip_id"],
                "core_id": cols["core_id"],
                "kind": cols["kind"],
                "hlo_module": lazy("hlo_module"),
                "hlo_op": lazy("hlo_op"),
                "hlo_category": lazy("hlo_category"),
                "flops": cols["flops"],
                "bytes_accessed": cols["bytes_accessed"],
                "program_id": cols["program_id"],
                "run_id": cols["run_id"],
                "collective": lazy("collective"),
                "bytes_transferred": cols["bytes_transferred"],
                "replica_group_size": cols["replica_group_size"],
                "step": cols["step"],
                "pid": cols["pid"],
                "process_name": pname,
                "app_service": pname,
            }
            out.update(tags)
            # span-labeled slice wins; the agent's universal tag fills
            # the rest (same precedence as the pb path)
            sl = cols["slice_id"]
            out["slice_id"] = np.where(sl != 0, sl,
                                       np.uint32(tags.get("slice_id", 0)))
            self.write_columns("profile.tpu_hlo_span", out, n)
        if n_mem:
            mem = {
                "time": shifted(cols["m_timestamp_ns"]),
                "device_id": cols["m_device_id"],
                "bytes_in_use": cols["m_bytes_in_use"],
                "peak_bytes_in_use": cols["m_peak_bytes_in_use"],
                "bytes_limit": cols["m_bytes_limit"],
                "largest_free_block": cols["m_largest_free_block"],
                "num_allocs": cols["m_num_allocs"],
                "pid": cols["m_pid"],
                "process_name": lazy("m_pname"),
            }
            mem.update(tags)
            self.write_columns("profile.tpu_memory", mem, n_mem)
        return n + n_mem

    def handle(self, header: FrameHeader, payload: bytes) -> int:
        fast = self._fast_decoder()
        if fast is not None:
            try:
                res = fast.decode(payload)
            except Exception:
                res = None
            if res is not None:
                return self._handle_cols(header, *res)
        batch = pb.TpuSpanBatch.FromString(payload)
        tags = self._agent_tags(header)
        off = self._clock_offset(header)
        rows = []
        for s in batch.spans:
            rows.append({
                "time": s.start_ns + off,
                "duration_ns": s.duration_ns,
                "device_id": s.device_id,
                "chip_id": s.chip_id,
                "core_id": s.core_id,
                "kind": int(s.kind),
                "hlo_module": s.hlo_module,
                "hlo_op": s.hlo_op,
                "hlo_category": s.hlo_category,
                "flops": s.flops,
                "bytes_accessed": s.bytes_accessed,
                "program_id": s.program_id,
                "run_id": s.run_id,
                "collective": s.collective,
                "bytes_transferred": s.bytes_transferred,
                "replica_group_size": s.replica_group_size,
                "step": s.step,
                "pid": s.pid,
                "process_name": s.process_name,
                "app_service": s.process_name,
                **{**tags, "slice_id": s.slice_id or tags.get("slice_id", 0)},
            })
        self.write("profile.tpu_hlo_span", rows)
        mem_rows = []
        for m in batch.memory:
            mem_rows.append({
                "time": m.timestamp_ns + off,
                "device_id": m.device_id,
                "bytes_in_use": m.bytes_in_use,
                "peak_bytes_in_use": m.peak_bytes_in_use,
                "bytes_limit": m.bytes_limit,
                "largest_free_block": m.largest_free_block,
                "num_allocs": m.num_allocs,
                "pid": m.pid,
                "process_name": m.process_name,
                **tags,
            })
        if mem_rows:
            self.write("profile.tpu_memory", mem_rows)
        return len(rows) + len(mem_rows)


class StepMetricsDecoder(Decoder):
    """STEP_METRICS JSON payloads -> profile.tpu_step_metrics.

    The payload is NOT protobuf (stepmetrics.py explains why); malformed
    frames raise ValueError and land on the decoder ledger as
    dropped/decode_error like any other bad payload."""

    MSG_TYPE = MessageType.STEP_METRICS

    def handle(self, header: FrameHeader, payload: bytes) -> int:
        from deepflow_tpu.tpuprobe.stepmetrics import decode_step_payload
        obj = decode_step_payload(payload)
        tags = self._agent_tags(header)
        off = self._clock_offset(header)
        pid = int(obj.get("pid") or 0)
        pname = str(obj.get("process_name") or "")
        rows = []
        for r in obj["records"]:
            t0 = int(r.get("time") or 0)
            t1 = int(r.get("end_ns") or 0)
            rows.append({
                "time": t0 + off,
                "end_ns": t1 + off,
                "latency_ns": int(r.get("latency_ns") or max(0, t1 - t0)),
                "run_id": int(r.get("run_id") or 0),
                "step": int(r.get("step") or 0),
                "job": str(r.get("job") or ""),
                "device_count": int(r.get("device_count") or 0),
                "device_skew_ns": int(r.get("device_skew_ns") or 0),
                "compute_ns": int(r.get("compute_ns") or 0),
                "collective_ns": int(r.get("collective_ns") or 0),
                "straggler_device": int(r.get("straggler_device") or 0),
                "straggler_lag_ns": int(r.get("straggler_lag_ns") or 0),
                "top_hlos": json.dumps(r.get("top_hlos") or [],
                                       separators=(",", ":")),
                "pid": pid,
                "process_name": pname,
                **tags,
            })
        self.write("profile.tpu_step_metrics", rows)
        return len(rows)


class PcapDecoder(Decoder):
    """PcapUpload -> data_dir/pcaps/<name>.pcap.gz (or memory when no
    data_dir). Reference: ingester pcap module."""

    MSG_TYPE = MessageType.PCAP
    MAX_MEMORY = 64
    _store_lock = threading.Lock()  # handle() must be safe under workers>1

    @staticmethod
    def _safe_name(name: str) -> str:
        """Wire-controlled names must never traverse paths."""
        import re
        cleaned = re.sub(r"[^A-Za-z0-9._-]", "_", os.path.basename(name))
        return cleaned.lstrip(".") or "unnamed"

    def handle(self, header: FrameHeader, payload: bytes) -> int:
        up = pb.PcapUpload.FromString(payload)
        safe = self._safe_name(up.name)
        entry = {"name": safe, "agent_id": up.agent_id or
                 header.agent_id, "start_ns": up.start_ns,
                 "packet_count": up.packet_count,
                 "bytes_gz": len(up.pcap_gz)}
        with self._store_lock:
            store = getattr(self.db, "pcap_store", None)
            if store is None:
                store = self.db.pcap_store = {"dir": None, "entries": []}
                if self.db.data_dir:
                    store["dir"] = os.path.join(self.db.data_dir, "pcaps")
                    os.makedirs(store["dir"], exist_ok=True)
            if store["dir"]:
                path = os.path.join(store["dir"], f"{safe}.pcap.gz")
                with open(path, "wb") as f:
                    f.write(up.pcap_gz)
                entry["path"] = path
            else:
                entry["data"] = up.pcap_gz
            store["entries"].append(entry)
            for old in store["entries"][:-self.MAX_MEMORY]:
                p = old.get("path")  # evicted captures must not leak disk
                if p:
                    try:
                        os.unlink(p)
                    except OSError:
                        pass
            del store["entries"][:-self.MAX_MEMORY]
        return 1


class FlowLogDecoder(Decoder):
    """FlowLogBatch -> flow_log.l4_flow_log / l7_flow_log. Registered for
    both L4_LOG and L7_LOG message types.

    Hot path: the native columnar wire decoder (native/pbcols.cpp) parses
    L4 rows straight into numpy arrays with the GIL RELEASED — which is
    what makes WORKERS > 1 genuinely scale across cores (reference: the
    Go ingester fans decode across cores,
    flow_metrics/flow_metrics.go:55-61; Python-object decode was
    GIL-bound). v6 or malformed batches fall back to the protobuf path.
    """

    MSG_TYPE = MessageType.L4_LOG
    # decode workers: >1 scales on multi-core hosts because the native
    # parse releases the GIL (set DF_INGEST_WORKERS to the core budget)
    try:
        WORKERS = max(1, int(os.environ.get("DF_INGEST_WORKERS", "1")
                             or 1))
    except ValueError:
        WORKERS = 1  # malformed env must not take the server down

    _IP_MEMO_MAX = 1 << 20  # distinct v4 addresses before a full reset

    def __init__(self, *args, **kw) -> None:
        super().__init__(*args, **kw)
        self._tl = threading.local()  # per-worker native decode buffers
        # uint32 ip -> (dotted str, packed bytes), memoized ACROSS batches
        # (real fleets see a bounded address set, so after warmup the per
        # batch cost drops to dict gets). Shared by workers: dict get/set
        # are GIL-atomic, a racy duplicate insert is harmless.
        self._ip_memo: dict[int, tuple[str, bytes]] = {}

    def _ip_views(self, ip4s: np.ndarray, ip4d: np.ndarray):
        """(src dotted, dst dotted, src packed, dst packed) row lists for
        two uint32 address columns; packed lists are None when no gpid
        table is attached (the only consumer of the bytes form)."""
        memo = self._ip_memo
        for u in np.unique(np.concatenate((ip4s, ip4d))).tolist():
            if u not in memo:
                if len(memo) >= self._IP_MEMO_MAX:
                    memo.clear()
                memo[u] = ("%d.%d.%d.%d" % (u >> 24 & 255, u >> 16 & 255,
                                            u >> 8 & 255, u & 255),
                           struct.pack(">I", u))
        src = [memo[x] for x in ip4s.tolist()]
        dst = [memo[x] for x in ip4d.tolist()]
        src_s = [t[0] for t in src]
        dst_s = [t[0] for t in dst]
        if self.gpid_table is None:
            return src_s, dst_s, None, None
        return src_s, dst_s, [t[1] for t in src], [t[1] for t in dst]

    def _fast_decoder(self):
        """Per-thread L4ColumnDecoder (its buffers are not shareable)."""
        dec = getattr(self._tl, "l4cols", False)
        if dec is False:
            try:
                from deepflow_tpu.native import L4ColumnDecoder
                dec = L4ColumnDecoder()
            except Exception:
                dec = None
            self._tl.l4cols = dec
        return dec

    def _fast_l7_decoder(self):
        """Per-thread L7ColumnDecoder (its buffers are not shareable)."""
        dec = getattr(self._tl, "l7cols", False)
        if dec is False:
            try:
                from deepflow_tpu.native import L7ColumnDecoder
                dec = L7ColumnDecoder()
            except Exception:
                dec = None
            self._tl.l7cols = dec
        return dec

    def _endpoint_cols(self, items, keys, src_s, dst_s) -> dict:
        """Protobuf-object front end of the shared resolution ladder."""
        return self._resolve_endpoint_cols(
            len(items),
            [bytes(k.ip_src) for k in keys],
            [bytes(k.ip_dst) for k in keys],
            [k.port_src for k in keys], [k.port_dst for k in keys],
            [int(k.proto) for k in keys],
            [f.gpid_0 for f in items], [f.gpid_1 for f in items],
            [f.pod_0 for f in items], [f.pod_1 for f in items],
            src_s, dst_s)

    def _resolve_endpoint_cols(self, n, ipb0, ipb1, ports0, ports1,
                               protos, agent_g0, agent_g1, pod0, pod1,
                               src_s, dst_s) -> dict:
        """gprocess/resource columns shared by the l4/l7 branches AND the
        native columnar fast path — ONE ladder, so the two decode paths
        cannot diverge on how the same traffic resolves. Agent values win
        for pod/gpid; everything else resolves via the controller gpid
        table / genesis ResourceIndex, deduped per distinct endpoint
        (reference: grpc_platformdata.go QueryIPV4Infos per-side fill).
        pod0/pod1 may be lists or a scalar broadcast; ipb0/ipb1 (bytes
        form, consumed only by the gpid join) may be None when no gpid
        table is attached."""
        def aslist(p):
            return _aslist(p, n)
        cols: dict = {}
        if self.gpid_table is None:
            cols["gprocess_id_0"] = agent_g0
            cols["gprocess_id_1"] = agent_g1
            cols["process_kname_0"] = ""
            cols["process_kname_1"] = ""
        else:
            # socket-inode scan entries give every flow endpoint a
            # gpid AND a process name, preload or not (reference:
            # linux_socket.rs scan -> grpc_platformdata.go join)
            nl = self.gpid_table.name_lookup
            cache: dict = {}

            def side(ipbs, ports, agents):
                gpids, names = [], []
                for ipb, port, proto, ag in zip(ipbs, ports, protos,
                                                agents):
                    k = (ipb, port, proto)
                    v = cache.get(k)
                    if v is None:
                        v = cache[k] = nl(ipb, port, proto)
                    gpids.append(ag or v[0])
                    names.append(v[1])
                return gpids, names
            cols["gprocess_id_0"], cols["process_kname_0"] = side(
                ipb0, ports0, agent_g0)
            cols["gprocess_id_1"], cols["process_kname_1"] = side(
                ipb1, ports1, agent_g1)
        if self.resources is not None and not self.resources.is_empty():
            res = self.resources.batch_resolver()
            rcache: dict = {}

            def resolve(s):
                t = rcache.get(s)
                if t is None:
                    t = rcache[s] = res(s)
                return t
            t0 = [resolve(s) for s in src_s]
            t1 = [resolve(s) for s in dst_s]
            cols["pod_0"] = [p or t.pod
                             for p, t in zip(aslist(pod0), t0)]
            cols["pod_1"] = [p or t.pod
                             for p, t in zip(aslist(pod1), t1)]
            for name in SIDE_RESOLVE_NAMES:
                cols[f"{name}_0"] = [getattr(t, name) for t in t0]
                cols[f"{name}_1"] = [getattr(t, name) for t in t1]
        elif self.resources is not None:
            # nothing can resolve: agent values / constant broadcast
            cols["pod_0"], cols["pod_1"] = pod0, pod1
            for name in SIDE_RESOLVE_NAMES:
                cols[f"{name}_0"] = ""
                cols[f"{name}_1"] = ""
        elif self.pod_index is not None and len(self.pod_index):
            pods = self.pod_index.snapshot()

            def pod_of(ip_str: str) -> str:
                pod = pods.get(ip_str)
                return pod.name if pod is not None else ""
            cols["pod_0"] = [p or pod_of(s)
                             for p, s in zip(aslist(pod0), src_s)]
            cols["pod_1"] = [p or pod_of(s)
                             for p, s in zip(aslist(pod1), dst_s)]
        else:
            cols["pod_0"], cols["pod_1"] = pod0, pod1
        return cols

    def handle(self, header: FrameHeader, payload: bytes) -> int:
        sampler = self.qos_sampler
        if sampler is not None and sampler.rate_for(header.org_id) < 1.0:
            # tenant under pressure: ride the pb row path so each record
            # can be judged individually — error/slow exemplars always
            # kept, bulk head-sampled deterministically by flow_id.  The
            # extra pb decode only happens for tenants ALREADY being
            # shed (rate < 1); nominal traffic keeps the native path
            # (the <2% overhead gate measures exactly this branch check).
            batch = pb.FlowLogBatch.FromString(payload)
            self._sample_batch(batch, header.org_id)
            return self._handle_pb(batch, header)
        fast = self._fast_decoder()
        if fast is not None:
            try:
                res = fast.decode(payload)
            except Exception:
                res = None
            # v6 rows ride the pb path (printable-string formatting and
            # 128-bit handling are not worth a native fork; v6 flows are
            # the rare case in TPU fleets)
            if res is not None and not res[1]["is_v6"].any():
                n_l4, cols, l7segs, arena = res
                tags = self._agent_tags(header)
                off = self._clock_offset(header)
                n = 0
                if n_l4:
                    n += self._handle_l4_cols(cols, n_l4, arena, tags, off)
                if l7segs:
                    # L4 columns are consumed above, so the L7 decoder's
                    # separate buffers may now be filled from the same
                    # payload (it walks only top-level field-2 records)
                    l7fast = self._fast_l7_decoder()
                    l7res = None
                    if l7fast is not None:
                        try:
                            l7res = l7fast.decode(payload)
                        except Exception:
                            l7res = None
                    if l7res is not None and l7res[0] and \
                            not l7res[1]["is_v6"].any():
                        n += self._handle_l7_cols(
                            l7res[1], l7res[0], l7res[2], tags, off)
                    else:  # v6 / overflow: pb-parse exactly those bytes
                        l7 = [pb.L7FlowLog.FromString(payload[o:o + ln])
                              for o, ln in l7segs]
                        n += self._handle_l7_list(l7, tags, off)
                return n
        batch = pb.FlowLogBatch.FromString(payload)
        return self._handle_pb(batch, header)

    def _sample_batch(self, batch, org_id: int) -> None:
        """Tail-aware adaptive sampling, in place: keep every error/slow
        exemplar, head-sample the bulk rest by flow_id (deterministic —
        a retransmitted copy makes the same call).  Every dropped record
        is ledgered on the qos.sample hop (reason=adaptive_sample) and
        the applied rate is recorded per org so queriers can reweight
        (kept_bulk / rate + exemplars)."""
        s = self.qos_sampler
        slow_ns = s.config.slow_exemplar_ms * 1e6
        if batch.l4:
            kept = [f for f in batch.l4 if s.keep(
                org_id, f.flow_id,
                exemplar=(f.retrans_tx or f.retrans_rx or f.zero_win_tx
                          or f.zero_win_rx
                          or f.rtt_us * 1000 >= slow_ns))]
            if len(kept) < len(batch.l4):
                del batch.l4[:]
                batch.l4.extend(kept)
        if batch.l7:
            err = (pb.CLIENT_ERROR, pb.SERVER_ERROR, pb.TIMEOUT)
            kept = [f for f in batch.l7 if s.keep(
                org_id, f.flow_id,
                exemplar=(f.response_status in err
                          or f.end_time_ns - f.start_time_ns >= slow_ns))]
            if len(kept) < len(batch.l7):
                del batch.l7[:]
                batch.l7.extend(kept)

    def _handle_pb(self, batch, header: FrameHeader) -> int:
        tags = self._agent_tags(header)
        # NTP normalization: shift this agent's absolute timestamps onto
        # the controller clock (reference corrects on-agent in rpc/ntp.rs;
        # here ingest-side so every telemetry family is covered at one
        # choke point). Sub-ms offsets are noise, not skew.
        off = self._clock_offset(header)
        n = 0
        if batch.l4:  # pure-pb fallback path (v6 / no native lib)
            # columnar build: one C-speed comprehension per column instead
            # of per-row dicts (measured ~3x on the ingest bench; row
            # building was the GIL-bound bottleneck, see Decoder.WORKERS)
            l4 = list(batch.l4)
            keys = [f.key for f in l4]
            src_d = [_ip_decode(k.ip_src) for k in keys]
            dst_d = [_ip_decode(k.ip_dst) for k in keys]
            src_s = [t[0] for t in src_d]
            dst_s = [t[0] for t in dst_d]
            endpoint_cols = self._endpoint_cols(l4, keys, src_s, dst_s)
            cols = {
                "time": [f.end_time_ns + off for f in l4],
                "flow_id": [f.flow_id for f in l4],
                "ip_src": src_s,
                "ip_dst": dst_s,
                "ip4_src": [t[1] for t in src_d],
                "ip4_dst": [t[1] for t in dst_d],
                "port_src": [k.port_src for k in keys],
                "port_dst": [k.port_dst for k in keys],
                "protocol": [int(k.proto) for k in keys],
                "tap_port": [k.tap_port for k in keys],
                "start_time": [f.start_time_ns + off for f in l4],
                "end_time": [f.end_time_ns + off for f in l4],
                "packet_tx": [f.packet_tx for f in l4],
                "packet_rx": [f.packet_rx for f in l4],
                "byte_tx": [f.byte_tx for f in l4],
                "byte_rx": [f.byte_rx for f in l4],
                "l7_request": [f.l7_request for f in l4],
                "l7_response": [f.l7_response for f in l4],
                "rtt": [f.rtt_us for f in l4],
                "art": [f.art_us for f in l4],
                "retrans_tx": [f.retrans_tx for f in l4],
                "retrans_rx": [f.retrans_rx for f in l4],
                "zero_win_tx": [f.zero_win_tx for f in l4],
                "zero_win_rx": [f.zero_win_rx for f in l4],
                "close_type": [_close_type_idx(f.close_type) for f in l4],
                "syn_count": [f.syn_count for f in l4],
                "synack_count": [f.synack_count for f in l4],
                "tunnel_type": [min(int(k.tunnel_type), 4) for k in keys],
                "tunnel_id": [k.tunnel_id for k in keys],
                **endpoint_cols,
            }
            cols.update(tags)  # constant per batch: scalar broadcast
            self.write_columns("flow_log.l4_flow_log", cols, len(l4))
            n += len(l4)
        if batch.l7:
            n += self._handle_l7_list(list(batch.l7), tags, off)
        return n

    def _handle_l4_cols(self, cols: dict, n: int, arena, tags: dict,
                        off: int) -> int:
        """Native columnar L4 path: numpy views from pbcols.cpp become
        store columns directly. Per-row Python work is deduped — ip
        strings and gpid endpoints resolve once per DISTINCT value, which
        is how real traffic behaves (bounded host/endpoint sets)."""
        ip4s, ip4d = cols["ip4_src"], cols["ip4_dst"]
        src_s, dst_s, ipb0, ipb1 = self._ip_views(ip4s, ip4d)

        # agent-labeled pods (usually empty -> scalar broadcast)
        def pods(which: str):
            lens = cols[f"{which}_len"]
            if not lens.any():
                return ""
            ab = arena.tobytes()
            return [ab[o:o + ln].decode("utf-8", "replace") if ln else ""
                    for o, ln in zip(cols[f"{which}_off"].tolist(),
                                     lens.tolist())]
        pod0, pod1 = pods("pod0"), pods("pod1")

        ep = self._resolve_endpoint_cols(
            n, ipb0, ipb1,
            cols["port_src"].tolist(), cols["port_dst"].tolist(),
            cols["proto"].tolist(),
            cols["gpid_0"].tolist(), cols["gpid_1"].tolist(),
            pod0, pod1, src_s, dst_s)

        if off:
            t_end = (cols["end_time_ns"].astype(np.int64)
                     + off).astype(np.uint64)
            t_start = (cols["start_time_ns"].astype(np.int64)
                       + off).astype(np.uint64)
        else:
            t_end, t_start = cols["end_time_ns"], cols["start_time_ns"]
        out = {
            "time": t_end,
            "flow_id": cols["flow_id"],
            "ip_src": src_s,
            "ip_dst": dst_s,
            "ip4_src": ip4s,
            "ip4_dst": ip4d,
            "port_src": cols["port_src"],
            "port_dst": cols["port_dst"],
            "protocol": cols["proto"],
            "tap_port": cols["tap_port"],
            "start_time": t_start,
            "end_time": t_end,
            "packet_tx": cols["packet_tx"],
            "packet_rx": cols["packet_rx"],
            "byte_tx": cols["byte_tx"],
            "byte_rx": cols["byte_rx"],
            "l7_request": cols["l7_request"],
            "l7_response": cols["l7_response"],
            "rtt": cols["rtt_us"],
            "art": cols["art_us"],
            "retrans_tx": cols["retrans_tx"],
            "retrans_rx": cols["retrans_rx"],
            "zero_win_tx": cols["zero_win_tx"],
            "zero_win_rx": cols["zero_win_rx"],
            "close_type": cols["close_type"],
            "syn_count": cols["syn_count"],
            "synack_count": cols["synack_count"],
            "tunnel_type": np.minimum(cols["tunnel_type"], 4),
            "tunnel_id": cols["tunnel_id"],
            **ep,
        }
        out.update(tags)
        self.write_columns("flow_log.l4_flow_log", out, n)
        return n

    def _handle_l7_cols(self, cols: dict, n: int, arena, tags: dict,
                        off: int) -> int:
        """Native columnar L7 path (pbcols.cpp DfL7Cols): numpy views
        become store columns directly; the ~35-key per-row dict build of
        the pb path disappears. String cells decode from the shared arena
        once per DISTINCT value (request types, domains, endpoints repeat
        heavily in real traffic). Must stay row-identical to
        _handle_l7_list — the golden parity test enforces it."""
        ab_cell: list = []  # arena.tobytes() computed only if strs() needs it
        smemo: dict[bytes, str] = {}

        def strs(name: str):
            """Arena (off,len) pairs -> python strings; scalar "" when the
            whole column is empty (constant broadcast, store-supported).
            Only for columns that MUST be python strings (the resolution
            ladder, kname merge) — store-bound columns use lazy() below."""
            lens = cols[f"{name}_len"]
            if not lens.any():
                return ""
            if not ab_cell:
                ab_cell.append(arena.tobytes())
            ab = ab_cell[0]
            get = smemo.get
            out = []
            for o, ln in zip(cols[f"{name}_off"].tolist(), lens.tolist()):
                if not ln:
                    out.append("")
                    continue
                b = ab[o:o + ln]
                s = get(b)
                if s is None:
                    s = smemo[b] = b.decode("utf-8", "replace")
                out.append(s)
            return out

        def lazy(name: str):
            """Store-bound string column: stays (arena, off, len) all the
            way into Dictionary.encode_arena, so cells are interned in C++
            under one lock and never become Python strings on the hot
            path. Scalar "" broadcast when the whole column is empty."""
            lens = cols[f"{name}_len"]
            if not lens.any():
                return ""
            return ArenaStrings(arena, cols[f"{name}_off"], lens)

        ip4s, ip4d = cols["ip4_src"], cols["ip4_dst"]
        src_s, dst_s, ipb0, ipb1 = self._ip_views(ip4s, ip4d)
        ep = self._resolve_endpoint_cols(
            n, ipb0, ipb1,
            cols["port_src"].tolist(), cols["port_dst"].tolist(),
            cols["proto"].tolist(),
            cols["gpid_0"].tolist(), cols["gpid_1"].tolist(),
            strs("pod_0"), strs("pod_1"), src_s, dst_s)

        if off:
            t_start = (cols["start_time_ns"].astype(np.int64)
                       + off).astype(np.uint64)
        else:
            t_start = cols["start_time_ns"]
        dur = np.maximum(
            cols["end_time_ns"].astype(np.int64)
            - cols["start_time_ns"].astype(np.int64), 0).astype(np.uint64)

        def kname_merge(agent_kn, resolved):
            """Agent-observed kernel thread name wins (sslprobe path);
            the socket-scan join fills the rest — same precedence as the
            pb path."""
            if not isinstance(agent_kn, list):  # all-empty broadcast
                return resolved
            return [a or r for a, r in
                    zip(agent_kn, _aslist(resolved, n))]

        out = {
            "time": t_start,
            "flow_id": cols["flow_id"],
            "ip_src": src_s,
            "ip_dst": dst_s,
            "port_src": cols["port_src"],
            "port_dst": cols["port_dst"],
            "tunnel_type": np.minimum(cols["tunnel_type"], 4),
            "tunnel_id": cols["tunnel_id"],
            "l7_protocol": cols["l7_protocol"],
            "version": lazy("version"),
            "request_type": lazy("request_type"),
            "request_domain": lazy("request_domain"),
            "request_resource": lazy("request_resource"),
            "endpoint": lazy("endpoint"),
            "request_id": cols["request_id"],
            "response_status": cols["response_status"],
            "response_code": cols["response_code"],
            "response_exception": lazy("response_exception"),
            "response_result": lazy("response_result"),
            "response_duration": dur,
            "trace_id": lazy("trace_id"),
            "span_id": lazy("span_id"),
            "parent_span_id": lazy("parent_span_id"),
            "x_request_id": lazy("x_request_id"),
            "syscall_trace_id_request": cols["syscall_trace_id_request"],
            "syscall_trace_id_response": cols["syscall_trace_id_response"],
            "syscall_thread_0": cols["syscall_thread_0"],
            "syscall_thread_1": cols["syscall_thread_1"],
            "captured_request_byte": cols["captured_request_byte"],
            "captured_response_byte": cols["captured_response_byte"],
            **ep,
            "process_kname_0": kname_merge(strs("process_kname_0"),
                                           ep["process_kname_0"]),
            "process_kname_1": kname_merge(strs("process_kname_1"),
                                           ep["process_kname_1"]),
            "attrs": lazy("attrs_json"),
        }
        out.update(tags)
        self.write_columns("flow_log.l7_flow_log", out, n)
        if self.trace_trees is not None:
            self._feed_trace_trees(out, n)
        return n

    def _handle_l7_list(self, l7: list, tags: dict, off: int) -> int:
        keys = [f.key for f in l7]
        src_s = [_ip_str(k.ip_src) for k in keys]
        dst_s = [_ip_str(k.ip_dst) for k in keys]
        endpoint_cols = self._endpoint_cols(l7, keys, src_s, dst_s)
        cols = {
            "time": [f.start_time_ns + off for f in l7],
            "flow_id": [f.flow_id for f in l7],
            "ip_src": src_s,
            "ip_dst": dst_s,
            "port_src": [k.port_src for k in keys],
            "port_dst": [k.port_dst for k in keys],
            "tunnel_type": [min(int(k.tunnel_type), 4) for k in keys],
            "tunnel_id": [k.tunnel_id for k in keys],
            "l7_protocol": [int(f.l7_protocol) for f in l7],
            "version": [f.version for f in l7],
            "request_type": [f.request_type for f in l7],
            "request_domain": [f.request_domain for f in l7],
            "request_resource": [f.request_resource for f in l7],
            "endpoint": [f.endpoint for f in l7],
            "request_id": [f.request_id for f in l7],
            "response_status": [int(f.response_status) for f in l7],
            "response_code": [f.response_code for f in l7],
            "response_exception": [f.response_exception for f in l7],
            "response_result": [f.response_result for f in l7],
            "response_duration": [
                max(0, f.end_time_ns - f.start_time_ns) for f in l7],
            "trace_id": [f.trace_id for f in l7],
            "span_id": [f.span_id for f in l7],
            "parent_span_id": [f.parent_span_id for f in l7],
            "x_request_id": [f.x_request_id for f in l7],
            "syscall_trace_id_request": [
                f.syscall_trace_id_request for f in l7],
            "syscall_trace_id_response": [
                f.syscall_trace_id_response for f in l7],
            "syscall_thread_0": [f.syscall_thread_0 for f in l7],
            "syscall_thread_1": [f.syscall_thread_1 for f in l7],
            "captured_request_byte": [
                f.captured_request_byte for f in l7],
            "captured_response_byte": [
                f.captured_response_byte for f in l7],
            **endpoint_cols,
            # agent-observed kernel thread name wins (sslprobe path);
            # the socket-scan join fills the rest (may be a scalar "")
            "process_kname_0": [
                f.process_kname_0 or n for f, n in zip(
                    l7, _aslist(endpoint_cols["process_kname_0"],
                                len(l7)))],
            "process_kname_1": [
                f.process_kname_1 or n for f, n in zip(
                    l7, _aslist(endpoint_cols["process_kname_1"],
                                len(l7)))],
            "attrs": [f.attrs_json for f in l7],
        }
        cols.update(tags)  # constant per batch: scalar broadcast
        self.write_columns("flow_log.l7_flow_log", cols, len(l7))
        if self.trace_trees is not None:
            self._feed_trace_trees(cols, len(l7))
        return len(l7)

    def _feed_trace_trees(self, cols: dict, n: int) -> None:
        """Traced rows (non-empty trace_id: typically a small subset)
        feed the ingest-time trace_tree precompute."""
        from deepflow_tpu.server.tracetree import span_from_l7

        def at(col, i):
            """Columns may be scalars (constant broadcast), lists,
            ndarrays, or lazy ArenaStrings (native columnar path)."""
            if isinstance(col, (list, np.ndarray, ArenaStrings)):
                return col[i]
            return col
        tids = cols["trace_id"]
        if isinstance(tids, ArenaStrings):
            if not tids.lens.any():
                return  # no row is traced: skip the scan entirely
            tids = tids.tolist()
        elif isinstance(tids, str):
            if not tids:
                return  # all-empty broadcast: nothing is traced
            tids = [tids] * n
        for i in range(n):
            tid = tids[i]
            if not tid:
                continue
            proto_i = int(at(cols["l7_protocol"], i))
            status_i = int(at(cols["response_status"], i))
            self.trace_trees.add_span(tid, span_from_l7({
                "time": at(cols["time"], i),
                "flow_id": at(cols["flow_id"], i),
                "request_id": at(cols["request_id"], i),
                "span_id": at(cols["span_id"], i),
                "parent_span_id": at(cols["parent_span_id"], i),
                "request_type": at(cols["request_type"], i),
                "endpoint": at(cols["endpoint"], i),
                "request_resource": at(cols["request_resource"], i),
                "app_service": at(cols["app_service"], i)
                if "app_service" in cols else "",
                "service_1": at(cols.get("service_1", ""), i),
                "host": at(cols.get("host", ""), i),
                "l7_protocol": (L7_PROTOS[proto_i]
                                if 0 <= proto_i < len(L7_PROTOS)
                                else "unknown"),
                "response_status": (RESPONSE_STATUS[status_i]
                                    if 0 <= status_i < len(RESPONSE_STATUS)
                                    else "unknown"),
                "response_code": at(cols["response_code"], i),
                "response_duration": at(cols["response_duration"], i),
                "ip_src": at(cols["ip_src"], i),
                "ip_dst": at(cols["ip_dst"], i),
                "x_request_id": at(cols["x_request_id"], i),
            }))


class MetricsDecoder(Decoder):
    """DocumentBatch -> flow_metrics.network/application 1s tables.
    1m rollups are produced by the datasource rollup job, not here.

    Hot path: native columnar decode (native/ingest.cpp
    df_decode_doc_cols) — FlowMeter/AppMeter fields land in numpy arrays
    under their store column names with the GIL released; HasField
    presence rides has_flow/has_app flag columns, ip emptiness rides
    ip_flags bits. Batches with non-v4 addresses (IP_FALLBACK bit) take
    the protobuf fallback whole, keeping v6 formatting parity exact."""

    MSG_TYPE = MessageType.METRICS

    _IP_MEMO_MAX = 1 << 20

    def __init__(self, *a, **kw) -> None:
        super().__init__(*a, **kw)
        self._tl = threading.local()  # per-worker native decode buffers
        self._ip_memo: dict[int, str] = {}  # u32 -> dotted, across batches

    def _fast_decoder(self):
        dec = getattr(self._tl, "doccols", False)
        if dec is False:
            try:
                from deepflow_tpu.native import DocColumnDecoder
                dec = DocColumnDecoder()
            except Exception:
                dec = None
            self._tl.doccols = dec
        return dec

    def _dotted(self, u32s: np.ndarray, flags: np.ndarray,
                empty_bit: int) -> list:
        """u32 addresses -> dotted strings; rows whose ip_flags carry
        empty_bit render "" (pb parity: absent/empty wire bytes decode
        to the empty string, not 0.0.0.0)."""
        memo = self._ip_memo
        out = []
        for u, fl in zip(u32s.tolist(), flags.tolist()):
            if fl & empty_bit:
                out.append("")
                continue
            s = memo.get(u)
            if s is None:
                if len(memo) >= self._IP_MEMO_MAX:
                    memo.clear()
                s = memo[u] = "%d.%d.%d.%d" % (
                    u >> 24 & 255, u >> 16 & 255, u >> 8 & 255, u & 255)
            out.append(s)
        return out

    def _handle_cols(self, header: FrameHeader, n: int, cols: dict,
                     arena) -> int:
        tags = self._agent_tags(header)
        off_s = round(self._clock_offset(header) / 1e9)
        flags = cols["ip_flags"]
        src_all = self._dotted(cols["ip4_src"], flags, IP_SRC_EMPTY)
        dst_all = self._dotted(cols["ip4_dst"], flags, IP_DST_EMPTY)
        if off_s:
            time_all = (cols["timestamp_s"].astype(np.int64)
                        + off_s).astype(np.uint64)
        else:
            time_all = cols["timestamp_s"]
        resolver = None
        if self.resources is not None and not self.resources.is_empty():
            resolver = self.resources.batch_resolver()

        def base_cols(idx: np.ndarray) -> tuple[dict, int]:
            ii = idx.tolist()
            src_s = [src_all[i] for i in ii]
            dst_s = [dst_all[i] for i in ii]
            out = {
                "time": time_all[idx],
                "ip_src": src_s,
                "ip_dst": dst_s,
                "server_port": cols["port"][idx],
            }
            if resolver is not None:
                t0 = [resolver(s) for s in src_s]
                t1 = [resolver(s) for s in dst_s]
                out["pod_0"] = [t.pod for t in t0]
                out["pod_1"] = [t.pod for t in t1]
                for name in SIDE_RESOLVE_NAMES:
                    out[f"{name}_0"] = [getattr(t, name) for t in t0]
                    out[f"{name}_1"] = [getattr(t, name) for t in t1]
            elif self.resources is not None:
                out["pod_0"] = ""
                out["pod_1"] = ""
                for name in SIDE_RESOLVE_NAMES:
                    out[f"{name}_0"] = ""
                    out[f"{name}_1"] = ""
            out.update(tags)
            return out, len(ii)

        n_rows = 0
        net_idx = np.flatnonzero(cols["has_flow"])
        if len(net_idx):
            c, k = base_cols(net_idx)
            c.update({
                "protocol": cols["proto"][net_idx],
                "direction": cols["direction"][net_idx],
            })
            for name in ("packet_tx", "packet_rx", "byte_tx", "byte_rx",
                         "flow_count", "new_flow", "closed_flow",
                         "rtt_sum", "rtt_count", "retrans", "syn_count",
                         "synack_count"):
                c[name] = cols[name][net_idx]
            self.write_columns("flow_metrics.network.1s", c, k)
            n_rows += k
        app_idx = np.flatnonzero(cols["has_app"])
        if len(app_idx):
            c, k = base_cols(app_idx)
            lens = cols["app_service_len"][app_idx]
            c["l7_protocol"] = cols["l7_protocol"][app_idx]
            c["app_service"] = (
                ArenaStrings(arena, cols["app_service_off"][app_idx],
                             lens) if lens.any() else "")
            for name in ("request", "response", "rrt_sum", "rrt_count",
                         "rrt_max", "error_client", "error_server",
                         "timeout"):
                c[name] = cols[name][app_idx]
            self.write_columns("flow_metrics.application.1s", c, k)
            n_rows += k
        return n_rows

    def handle(self, header: FrameHeader, payload: bytes) -> int:
        fast = self._fast_decoder()
        if fast is not None:
            try:
                res = fast.decode(payload)
            except Exception:
                res = None
            # any non-{empty,v4} address (v6) -> pb path for the whole
            # batch: printable v6 formatting stays in one place
            if res is not None and \
                    not (res[1]["ip_flags"] & IP_FALLBACK).any():
                return self._handle_cols(header, res[0], res[1], res[2])
        batch = pb.DocumentBatch.FromString(payload)
        tags = self._agent_tags(header)
        off_s = round(self._clock_offset(header) / 1e9)  # table is 1s-grain
        n = 0

        def base_cols(docs):
            src_s = [_ip_str(d.tag.ip_src) for d in docs]
            dst_s = [_ip_str(d.tag.ip_dst) for d in docs]
            cols = {
                "time": [d.timestamp_s + off_s for d in docs],
                "ip_src": src_s,
                "ip_dst": dst_s,
                "server_port": [d.tag.port for d in docs],
            }
            if self.resources is not None and not self.resources.is_empty():
                # per-side universal tags on metrics rows: this is what
                # makes "group any metric by any resource" work
                res = self.resources.batch_resolver()
                t0 = [res(s) for s in src_s]
                t1 = [res(s) for s in dst_s]
                cols["pod_0"] = [t.pod for t in t0]
                cols["pod_1"] = [t.pod for t in t1]
                for name in SIDE_RESOLVE_NAMES:
                    cols[f"{name}_0"] = [getattr(t, name) for t in t0]
                    cols[f"{name}_1"] = [getattr(t, name) for t in t1]
            elif self.resources is not None:
                # keep the exported row shape stable vs the resolving case
                cols["pod_0"] = ""
                cols["pod_1"] = ""
                for name in SIDE_RESOLVE_NAMES:
                    cols[f"{name}_0"] = ""
                    cols[f"{name}_1"] = ""
            cols.update(tags)  # constant per batch: scalar broadcast
            return cols

        net = [d for d in batch.docs if d.HasField("flow_meter")]
        if net:
            ms = [d.flow_meter for d in net]
            cols = base_cols(net)
            cols.update({
                "protocol": [int(d.tag.proto) for d in net],
                "direction": [d.tag.direction for d in net],
                "packet_tx": [m.packet_tx for m in ms],
                "packet_rx": [m.packet_rx for m in ms],
                "byte_tx": [m.byte_tx for m in ms],
                "byte_rx": [m.byte_rx for m in ms],
                "flow_count": [m.flow_count for m in ms],
                "new_flow": [m.new_flow for m in ms],
                "closed_flow": [m.closed_flow for m in ms],
                "rtt_sum": [m.rtt_sum_us for m in ms],
                "rtt_count": [m.rtt_count for m in ms],
                "retrans": [m.retrans for m in ms],
                "syn_count": [m.syn_count for m in ms],
                "synack_count": [m.synack_count for m in ms],
            })
            self.write_columns("flow_metrics.network.1s", cols, len(net))
            n += len(net)
        app = [d for d in batch.docs if d.HasField("app_meter")]
        if app:
            ms = [d.app_meter for d in app]
            cols = base_cols(app)
            cols.update({
                "l7_protocol": [int(d.tag.l7_protocol) for d in app],
                "app_service": [d.tag.app_service for d in app],
                "request": [m.request for m in ms],
                "response": [m.response for m in ms],
                "rrt_sum": [m.rrt_sum_us for m in ms],
                "rrt_count": [m.rrt_count for m in ms],
                "rrt_max": [m.rrt_max_us for m in ms],
                "error_client": [m.error_client for m in ms],
                "error_server": [m.error_server for m in ms],
                "timeout": [m.timeout for m in ms],
            })
            self.write_columns("flow_metrics.application.1s", cols, len(app))
            n += len(app)
        return n


class StatsDecoder(Decoder):
    """StatsBatch -> deepflow_system (self-telemetry)."""

    MSG_TYPE = MessageType.DFSTATS

    def handle(self, header: FrameHeader, payload: bytes) -> int:
        batch = pb.StatsBatch.FromString(payload)
        tags = self._agent_tags(header)
        off = self._clock_offset(header)
        rows = []
        for m in batch.metrics:
            tag_json = json.dumps(dict(m.tags), sort_keys=True)
            for vname, v in m.values.items():
                rows.append({
                    "time": m.timestamp_ns + off,
                    "metric_name": m.name,
                    "tag_json": tag_json,
                    "value_name": vname,
                    "value": v,
                    **tags,
                })
        self.write("deepflow_system.deepflow_system", rows)
        return len(rows)


class EventDecoder(Decoder):
    """EventBatch -> event.event, plus the file-IO aggregation reducer
    (reference: ingester/event/decoder/file_agg_reducer.go): raw
    file-io-read/write events roll up into per-(pid, path, op) minute
    windows in event.file_agg."""

    MSG_TYPE = MessageType.EVENT

    WINDOW_NS = 60 * 1_000_000_000
    GRACE_NS = 5 * 1_000_000_000

    def __init__(self, *a, **kw) -> None:
        super().__init__(*a, **kw)
        # (window_ns, pid, path, op, tags_json) -> [count, bytes, max, sum]
        # guarded by _agg_lock: this decoder is stateful, so the base
        # class's WORKERS>1 knob must not corrupt the windows
        self._agg: dict[tuple, list] = {}
        self._agg_lock = threading.Lock()
        self._watermark = 0

    def handle(self, header: FrameHeader, payload: bytes) -> int:
        batch = pb.EventBatch.FromString(payload)
        tags = self._agent_tags(header)
        off = self._clock_offset(header)
        rows = [{
            "time": e.timestamp_ns + off,
            "event_type": e.event_type,
            "resource_type": e.resource_type,
            "resource_name": e.resource_name,
            "pid": e.pid,
            "description": e.description,
            "attrs": json.dumps(dict(e.attrs), sort_keys=True),
            **tags,
        } for e in batch.events]
        self.write("event.event", rows)
        # tags are constant per batch: serialize ONCE, not per io event
        tags_json = json.dumps(tags, sort_keys=True)
        for e in batch.events:
            if e.event_type in ("file-io-read", "file-io-write"):
                self._reduce_file_io(e, tags_json)
        self._flush_agg()
        return len(rows)

    def _reduce_file_io(self, e, tags_json: str) -> None:
        op = 0 if e.event_type == "file-io-read" else 1
        window = e.timestamp_ns - e.timestamp_ns % self.WINDOW_NS
        try:
            latency = int(e.attrs.get("latency_ns", "0"))
            nbytes = int(e.attrs.get("bytes", "0"))
        except ValueError:
            latency = nbytes = 0
        key = (window, e.pid, e.resource_name, op, tags_json)
        with self._agg_lock:
            acc = self._agg.get(key)
            if acc is None:
                acc = self._agg[key] = [0, 0, 0, 0]
            acc[0] += 1
            acc[1] += nbytes
            acc[2] = max(acc[2], latency)
            acc[3] += latency
            if e.timestamp_ns > self._watermark:
                self._watermark = e.timestamp_ns

    def _flush_agg(self, force: bool = False) -> None:
        """Emit windows the watermark has passed (late events within the
        grace period still merge; anything later starts a fresh row —
        counts stay correct, the window just splits)."""
        rows = []
        with self._agg_lock:
            limit = self._watermark - self.WINDOW_NS - self.GRACE_NS
            for key in [k for k in self._agg
                        if force or k[0] <= limit]:
                window, pid, path, op, tags_json = key
                count, nbytes, mx, total = self._agg.pop(key)
                rows.append({
                    "time": window, "pid": pid, "path": path, "op": op,
                    "count": count, "bytes": nbytes,
                    "max_latency_ns": mx, "sum_latency_ns": total,
                    **json.loads(tags_json),
                })
        if rows:
            self.write("event.file_agg", rows)

    def flush(self) -> None:
        """Final flush (server shutdown / tests)."""
        self._flush_agg(force=True)


def _aslist(v, n: int) -> list:
    """Scalar column broadcast -> per-row list (store columns may be
    scalars meaning 'this value for every row')."""
    if isinstance(v, ArenaStrings):
        return v.tolist()
    return v if isinstance(v, list) else [v] * n


_IP_CACHE: dict[bytes, tuple[str, int]] = {}
_IP_CACHE_MAX = 1 << 16


def _ip_decode(raw: bytes) -> tuple[str, int]:
    """raw bytes -> (dotted string, u32). Memoized: real traffic repeats a
    bounded host set, so the formatting cost is paid once per address."""
    hit = _IP_CACHE.get(raw)
    if hit is not None:
        return hit
    if len(raw) == 4:
        val = ("%d.%d.%d.%d" % (raw[0], raw[1], raw[2], raw[3]),
               int.from_bytes(raw, "big"))
    elif not raw:
        val = ("", 0)
    else:
        import ipaddress
        try:
            val = (str(ipaddress.ip_address(raw)), 0)
        except ValueError:
            val = (raw.hex(), 0)
    if len(_IP_CACHE) >= _IP_CACHE_MAX:
        _IP_CACHE.clear()  # coarse reset beats per-entry LRU bookkeeping
    _IP_CACHE[bytes(raw)] = val
    return val


def _ip_str(raw: bytes) -> str:
    return _ip_decode(raw)[0]


def _ip4_u32(raw: bytes) -> int:
    return _ip_decode(raw)[1]


def _close_type_idx(name: str) -> int:
    try:
        return CLOSE_TYPES.index(name or "unknown")
    except ValueError:
        return 0


ALL_DECODERS = [ProfileDecoder, TpuSpanDecoder, StepMetricsDecoder,
                FlowLogDecoder, MetricsDecoder, StatsDecoder, EventDecoder]
