"""Per-message-type decoders: pb payloads -> tag-injected store rows.

Reference analog: server/ingester/*/decoder (e.g. profile/decoder/decoder.go
:190 handleProfileData, flow_log/decoder/decoder.go:151 Run). Each decoder
owns one receiver queue, runs on its own thread, and writes columnar batches.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading

from deepflow_tpu.codec import FrameHeader, MessageType
from deepflow_tpu.proto import pb
from deepflow_tpu.store.db import Database
from deepflow_tpu.store.schema import (
    L4_PROTOS, L7_PROTOS, PROFILE_EVENT_TYPES, RESPONSE_STATUS,
    TPU_SPAN_KINDS, CLOSE_TYPES)
from deepflow_tpu.server.platform_info import PlatformInfoTable

log = logging.getLogger("df.decoder")


class Decoder:
    """Base: drain one queue, decode, write. Subclasses set MSG_TYPE."""

    MSG_TYPE: MessageType

    WORKERS = 1  # ingest parallelism hook (reference: per-type decoder
    # queues with N workers). MEASURED on this design: >1 worker does not
    # help (56k rows/s at 1, 54k at 2, 52k at 4) because the cost is
    # GIL-bound python row building, not protobuf parsing (upb releases
    # the GIL) — so the default stays 1; the knob exists for a future
    # native row builder. Row ORDER across workers is not guaranteed.

    def __init__(self, q: queue.Queue, db: Database,
                 platform: PlatformInfoTable, exporters=None,
                 pod_index=None, gpid_table=None,
                 workers: int | None = None) -> None:
        self.q = q
        self.db = db
        self.platform = platform
        self.exporters = exporters
        self.pod_index = pod_index  # K8s genesis IP->pod (optional)
        self.gpid_table = gpid_table  # controller GpidAllocator (optional)
        self.workers = workers if workers is not None else self.WORKERS
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._stats_lock = threading.Lock()
        self.stats = {"batches": 0, "rows": 0, "errors": 0}

    def start(self) -> "Decoder":
        for i in range(max(1, self.workers)):
            t = threading.Thread(
                target=self._run,
                name=f"df-decoder-{self.MSG_TYPE.name}-{i}", daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads = []

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                header, payload = self.q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                n = self.handle(header, payload)
                with self._stats_lock:
                    self.stats["batches"] += 1
                    self.stats["rows"] += n
            except Exception:
                with self._stats_lock:
                    self.stats["errors"] += 1
                log.exception("decode error (%s)", self.MSG_TYPE.name)

    def handle(self, header: FrameHeader, payload: bytes) -> int:
        raise NotImplementedError

    def write(self, table_name: str, rows: list[dict]) -> None:
        """Append + feed the re-export pipeline (reference: exporters)."""
        self.db.table(table_name).append_rows(rows)
        if self.exporters is not None and rows:
            self.exporters.feed(table_name, rows)


class ProfileDecoder(Decoder):
    """ProfileBatch -> profile.in_process_profile."""

    MSG_TYPE = MessageType.PROFILE

    def handle(self, header: FrameHeader, payload: bytes) -> int:
        batch = pb.ProfileBatch.FromString(payload)
        tags = self.platform.tags_for(header.agent_id)
        rows = []
        for p in batch.profiles:
            rows.append({
                "time": p.timestamp_ns,
                "app_service": p.app_service or p.process_name,
                "process_name": p.process_name,
                "event_type": int(p.event_type),
                "profiler": p.profiler,
                "pid": p.pid,
                "tid": p.tid,
                "thread_name": p.thread_name,
                "stack": p.stack.decode("utf-8", "replace"),
                "value": p.value,
                "count": p.count,
                **tags,
            })
        self.write("profile.in_process_profile", rows)
        return len(rows)


class TpuSpanDecoder(Decoder):
    """TpuSpanBatch -> profile.tpu_hlo_span."""

    MSG_TYPE = MessageType.TPU_SPAN

    def handle(self, header: FrameHeader, payload: bytes) -> int:
        batch = pb.TpuSpanBatch.FromString(payload)
        tags = self.platform.tags_for(header.agent_id)
        rows = []
        for s in batch.spans:
            rows.append({
                "time": s.start_ns,
                "duration_ns": s.duration_ns,
                "device_id": s.device_id,
                "chip_id": s.chip_id,
                "core_id": s.core_id,
                "kind": int(s.kind),
                "hlo_module": s.hlo_module,
                "hlo_op": s.hlo_op,
                "hlo_category": s.hlo_category,
                "flops": s.flops,
                "bytes_accessed": s.bytes_accessed,
                "program_id": s.program_id,
                "run_id": s.run_id,
                "collective": s.collective,
                "bytes_transferred": s.bytes_transferred,
                "replica_group_size": s.replica_group_size,
                "step": s.step,
                "pid": s.pid,
                "process_name": s.process_name,
                "app_service": s.process_name,
                **{**tags, "slice_id": s.slice_id or tags.get("slice_id", 0)},
            })
        self.write("profile.tpu_hlo_span", rows)
        return len(rows)


class PcapDecoder(Decoder):
    """PcapUpload -> data_dir/pcaps/<name>.pcap.gz (or memory when no
    data_dir). Reference: ingester pcap module."""

    MSG_TYPE = MessageType.PCAP
    MAX_MEMORY = 64
    _store_lock = threading.Lock()  # handle() must be safe under workers>1

    @staticmethod
    def _safe_name(name: str) -> str:
        """Wire-controlled names must never traverse paths."""
        import re
        cleaned = re.sub(r"[^A-Za-z0-9._-]", "_", os.path.basename(name))
        return cleaned.lstrip(".") or "unnamed"

    def handle(self, header: FrameHeader, payload: bytes) -> int:
        up = pb.PcapUpload.FromString(payload)
        safe = self._safe_name(up.name)
        entry = {"name": safe, "agent_id": up.agent_id or
                 header.agent_id, "start_ns": up.start_ns,
                 "packet_count": up.packet_count,
                 "bytes_gz": len(up.pcap_gz)}
        with self._store_lock:
            store = getattr(self.db, "pcap_store", None)
            if store is None:
                store = self.db.pcap_store = {"dir": None, "entries": []}
                if self.db.data_dir:
                    store["dir"] = os.path.join(self.db.data_dir, "pcaps")
                    os.makedirs(store["dir"], exist_ok=True)
            if store["dir"]:
                path = os.path.join(store["dir"], f"{safe}.pcap.gz")
                with open(path, "wb") as f:
                    f.write(up.pcap_gz)
                entry["path"] = path
            else:
                entry["data"] = up.pcap_gz
            store["entries"].append(entry)
            for old in store["entries"][:-self.MAX_MEMORY]:
                p = old.get("path")  # evicted captures must not leak disk
                if p:
                    try:
                        os.unlink(p)
                    except OSError:
                        pass
            del store["entries"][:-self.MAX_MEMORY]
        return 1


class FlowLogDecoder(Decoder):
    """FlowLogBatch -> flow_log.l4_flow_log / l7_flow_log. Registered for
    both L4_LOG and L7_LOG message types."""

    MSG_TYPE = MessageType.L4_LOG

    def _gpid(self, ip: bytes, port: int, proto: int) -> int:
        if self.gpid_table is None:
            return 0
        return self.gpid_table.lookup(bytes(ip), port, proto)

    def handle(self, header: FrameHeader, payload: bytes) -> int:
        batch = pb.FlowLogBatch.FromString(payload)
        tags = self.platform.tags_for(header.agent_id)
        # one snapshot per batch, not two lock round-trips per row
        pods = (self.pod_index.snapshot()
                if self.pod_index is not None else {})

        def pod_of(ip_str: str) -> str:
            pod = pods.get(ip_str)
            return pod.name if pod is not None else ""

        n = 0
        if batch.l4:
            rows = []
            for f in batch.l4:
                src_s, dst_s = _ip_str(f.key.ip_src), _ip_str(f.key.ip_dst)
                rows.append({
                    "time": f.end_time_ns,
                    "flow_id": f.flow_id,
                    "ip_src": src_s,
                    "ip_dst": dst_s,
                    "ip4_src": _ip4_u32(f.key.ip_src),
                    "ip4_dst": _ip4_u32(f.key.ip_dst),
                    "port_src": f.key.port_src,
                    "port_dst": f.key.port_dst,
                    "protocol": int(f.key.proto),
                    "tap_port": f.key.tap_port,
                    "start_time": f.start_time_ns,
                    "end_time": f.end_time_ns,
                    "packet_tx": f.packet_tx, "packet_rx": f.packet_rx,
                    "byte_tx": f.byte_tx, "byte_rx": f.byte_rx,
                    "l7_request": f.l7_request, "l7_response": f.l7_response,
                    "rtt": f.rtt_us, "art": f.art_us,
                    "retrans_tx": f.retrans_tx, "retrans_rx": f.retrans_rx,
                    "zero_win_tx": f.zero_win_tx, "zero_win_rx": f.zero_win_rx,
                    "close_type": _close_type_idx(f.close_type),
                    "syn_count": f.syn_count, "synack_count": f.synack_count,
                    "tunnel_type": min(int(f.key.tunnel_type), 4),
                    "tunnel_id": f.key.tunnel_id,
                    "gprocess_id_0": f.gpid_0 or self._gpid(
                        f.key.ip_src, f.key.port_src, int(f.key.proto)),
                    "gprocess_id_1": f.gpid_1 or self._gpid(
                        f.key.ip_dst, f.key.port_dst, int(f.key.proto)),
                    "pod_0": f.pod_0 or pod_of(src_s),
                    "pod_1": f.pod_1 or pod_of(dst_s),
                    **tags,
                })
            self.write("flow_log.l4_flow_log", rows)
            n += len(rows)
        if batch.l7:
            rows = []
            for f in batch.l7:
                src_s, dst_s = _ip_str(f.key.ip_src), _ip_str(f.key.ip_dst)
                rows.append({
                    "time": f.start_time_ns,
                    "flow_id": f.flow_id,
                    "ip_src": src_s,
                    "ip_dst": dst_s,
                    "port_src": f.key.port_src,
                    "port_dst": f.key.port_dst,
                    "tunnel_type": min(int(f.key.tunnel_type), 4),
                    "tunnel_id": f.key.tunnel_id,
                    "l7_protocol": int(f.l7_protocol),
                    "version": f.version,
                    "request_type": f.request_type,
                    "request_domain": f.request_domain,
                    "request_resource": f.request_resource,
                    "endpoint": f.endpoint,
                    "request_id": f.request_id,
                    "response_status": int(f.response_status),
                    "response_code": f.response_code,
                    "response_exception": f.response_exception,
                    "response_result": f.response_result,
                    "response_duration": max(0, f.end_time_ns - f.start_time_ns),
                    "trace_id": f.trace_id,
                    "span_id": f.span_id,
                    "parent_span_id": f.parent_span_id,
                    "x_request_id": f.x_request_id,
                    "syscall_trace_id_request": f.syscall_trace_id_request,
                    "syscall_trace_id_response": f.syscall_trace_id_response,
                    "syscall_thread_0": f.syscall_thread_0,
                    "syscall_thread_1": f.syscall_thread_1,
                    "captured_request_byte": f.captured_request_byte,
                    "captured_response_byte": f.captured_response_byte,
                    "gprocess_id_0": f.gpid_0 or self._gpid(
                        f.key.ip_src, f.key.port_src, int(f.key.proto)),
                    "gprocess_id_1": f.gpid_1 or self._gpid(
                        f.key.ip_dst, f.key.port_dst, int(f.key.proto)),
                    "pod_0": f.pod_0 or pod_of(src_s),
                    "pod_1": f.pod_1 or pod_of(dst_s),
                    "process_kname_0": f.process_kname_0,
                    "process_kname_1": f.process_kname_1,
                    "attrs": f.attrs_json,
                    **tags,
                })
            self.write("flow_log.l7_flow_log", rows)
            n += len(rows)
        return n


class MetricsDecoder(Decoder):
    """DocumentBatch -> flow_metrics.network/application 1s tables.
    1m rollups are produced by the datasource rollup job, not here."""

    MSG_TYPE = MessageType.METRICS

    def handle(self, header: FrameHeader, payload: bytes) -> int:
        batch = pb.DocumentBatch.FromString(payload)
        tags = self.platform.tags_for(header.agent_id)
        net_rows, app_rows = [], []
        for d in batch.docs:
            tag = d.tag
            base = {
                "time": d.timestamp_s,
                "ip_src": _ip_str(tag.ip_src),
                "ip_dst": _ip_str(tag.ip_dst),
                "server_port": tag.port,
                **tags,
            }
            if d.HasField("flow_meter"):
                m = d.flow_meter
                net_rows.append({
                    **base,
                    "protocol": int(tag.proto),
                    "direction": tag.direction,
                    "packet_tx": m.packet_tx, "packet_rx": m.packet_rx,
                    "byte_tx": m.byte_tx, "byte_rx": m.byte_rx,
                    "flow_count": m.flow_count, "new_flow": m.new_flow,
                    "closed_flow": m.closed_flow,
                    "rtt_sum": m.rtt_sum_us, "rtt_count": m.rtt_count,
                    "retrans": m.retrans,
                    "syn_count": m.syn_count, "synack_count": m.synack_count,
                })
            if d.HasField("app_meter"):
                m = d.app_meter
                app_rows.append({
                    **base,
                    "l7_protocol": int(tag.l7_protocol),
                    "app_service": tag.app_service,
                    "request": m.request, "response": m.response,
                    "rrt_sum": m.rrt_sum_us, "rrt_count": m.rrt_count,
                    "rrt_max": m.rrt_max_us,
                    "error_client": m.error_client,
                    "error_server": m.error_server,
                    "timeout": m.timeout,
                })
        if net_rows:
            self.write("flow_metrics.network.1s", net_rows)
        if app_rows:
            self.write("flow_metrics.application.1s", app_rows)
        return len(net_rows) + len(app_rows)


class StatsDecoder(Decoder):
    """StatsBatch -> deepflow_system (self-telemetry)."""

    MSG_TYPE = MessageType.DFSTATS

    def handle(self, header: FrameHeader, payload: bytes) -> int:
        batch = pb.StatsBatch.FromString(payload)
        tags = self.platform.tags_for(header.agent_id)
        rows = []
        for m in batch.metrics:
            tag_json = json.dumps(dict(m.tags), sort_keys=True)
            for vname, v in m.values.items():
                rows.append({
                    "time": m.timestamp_ns,
                    "metric_name": m.name,
                    "tag_json": tag_json,
                    "value_name": vname,
                    "value": v,
                    **tags,
                })
        self.write("deepflow_system.deepflow_system", rows)
        return len(rows)


class EventDecoder(Decoder):
    """EventBatch -> event.event, plus the file-IO aggregation reducer
    (reference: ingester/event/decoder/file_agg_reducer.go): raw
    file-io-read/write events roll up into per-(pid, path, op) minute
    windows in event.file_agg."""

    MSG_TYPE = MessageType.EVENT

    WINDOW_NS = 60 * 1_000_000_000
    GRACE_NS = 5 * 1_000_000_000

    def __init__(self, *a, **kw) -> None:
        super().__init__(*a, **kw)
        # (window_ns, pid, path, op, tags_json) -> [count, bytes, max, sum]
        # guarded by _agg_lock: this decoder is stateful, so the base
        # class's WORKERS>1 knob must not corrupt the windows
        self._agg: dict[tuple, list] = {}
        self._agg_lock = threading.Lock()
        self._watermark = 0

    def handle(self, header: FrameHeader, payload: bytes) -> int:
        batch = pb.EventBatch.FromString(payload)
        tags = self.platform.tags_for(header.agent_id)
        rows = [{
            "time": e.timestamp_ns,
            "event_type": e.event_type,
            "resource_type": e.resource_type,
            "resource_name": e.resource_name,
            "pid": e.pid,
            "description": e.description,
            "attrs": json.dumps(dict(e.attrs), sort_keys=True),
            **tags,
        } for e in batch.events]
        self.write("event.event", rows)
        # tags are constant per batch: serialize ONCE, not per io event
        tags_json = json.dumps(tags, sort_keys=True)
        for e in batch.events:
            if e.event_type in ("file-io-read", "file-io-write"):
                self._reduce_file_io(e, tags_json)
        self._flush_agg()
        return len(rows)

    def _reduce_file_io(self, e, tags_json: str) -> None:
        op = 0 if e.event_type == "file-io-read" else 1
        window = e.timestamp_ns - e.timestamp_ns % self.WINDOW_NS
        try:
            latency = int(e.attrs.get("latency_ns", "0"))
            nbytes = int(e.attrs.get("bytes", "0"))
        except ValueError:
            latency = nbytes = 0
        key = (window, e.pid, e.resource_name, op, tags_json)
        with self._agg_lock:
            acc = self._agg.get(key)
            if acc is None:
                acc = self._agg[key] = [0, 0, 0, 0]
            acc[0] += 1
            acc[1] += nbytes
            acc[2] = max(acc[2], latency)
            acc[3] += latency
            if e.timestamp_ns > self._watermark:
                self._watermark = e.timestamp_ns

    def _flush_agg(self, force: bool = False) -> None:
        """Emit windows the watermark has passed (late events within the
        grace period still merge; anything later starts a fresh row —
        counts stay correct, the window just splits)."""
        rows = []
        with self._agg_lock:
            limit = self._watermark - self.WINDOW_NS - self.GRACE_NS
            for key in [k for k in self._agg
                        if force or k[0] <= limit]:
                window, pid, path, op, tags_json = key
                count, nbytes, mx, total = self._agg.pop(key)
                rows.append({
                    "time": window, "pid": pid, "path": path, "op": op,
                    "count": count, "bytes": nbytes,
                    "max_latency_ns": mx, "sum_latency_ns": total,
                    **json.loads(tags_json),
                })
        if rows:
            self.write("event.file_agg", rows)

    def flush(self) -> None:
        """Final flush (server shutdown / tests)."""
        self._flush_agg(force=True)


def _ip_str(raw: bytes) -> str:
    import ipaddress
    if not raw:
        return ""
    try:
        return str(ipaddress.ip_address(raw))
    except ValueError:
        return raw.hex()


def _ip4_u32(raw: bytes) -> int:
    if len(raw) == 4:
        return int.from_bytes(raw, "big")
    return 0


def _close_type_idx(name: str) -> int:
    try:
        return CLOSE_TYPES.index(name or "unknown")
    except ValueError:
        return 0


ALL_DECODERS = [ProfileDecoder, TpuSpanDecoder, FlowLogDecoder,
                MetricsDecoder, StatsDecoder, EventDecoder]
