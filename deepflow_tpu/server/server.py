"""deepflow-tpu server wiring: receiver + decoders + querier (+ controller).

Reference analog: server/ingester/ingester/ingester.go:69 (Start: configs,
receiver, modules) combined with server/cmd/server/main.go (one process).
"""

from __future__ import annotations

import json
import logging
import threading
import time

from deepflow_tpu.codec import MessageType
from deepflow_tpu.server.decoders import (
    EventDecoder, FlowLogDecoder, MetricsDecoder, ProfileDecoder,
    StatsDecoder, StepMetricsDecoder, TpuSpanDecoder)
from deepflow_tpu.server.platform_info import PlatformInfoTable
from deepflow_tpu.server.querier import QuerierAPI, QuerierHTTP
from deepflow_tpu.server.receiver import Receiver
from deepflow_tpu.store.db import Database

log = logging.getLogger("df.server")


class Server:
    def __init__(self, host: str = "127.0.0.1", ingest_port: int = 20033,
                 query_port: int = 20416, data_dir: str | None = None,
                 sync_port: int = 20035, enable_controller: bool = False,
                 ha_lease_path: str | None = None,
                 ha_k8s_lease: str | None = None,
                 ingest_workers: int | None = None,
                 query_host: str | None = None,
                 selfmon: bool | None = None,
                 deadman_window_s: float = 15.0,
                 selfstats_interval_s: float = 10.0,
                 api_token: str | None = None,
                 shard_id: int = 0,
                 cluster_seed: str | None = None,
                 cluster_advertise: str | None = None,
                 fanout_timeout_s: float = 5.0,
                 fanout_hedge_delay_s: float = 0.25,
                 replication: int = 0,
                 storage: bool = False,
                 flush_interval_s: float = 1.0,
                 compact_interval_s: float = 60.0,
                 scrub_interval_s: float = 30.0,
                 storage_max_bytes: int = 0,
                 role: str = "ingest",
                 objstore: str | None = None,
                 objstore_mirrors=None,
                 segcache_max_bytes: int = 256 << 20,
                 publish_interval_s: float = 2.0,
                 readtier_poll_s: float = 2.0,
                 qos_config=None) -> None:
        # disaggregated storage (store/objstore.py + store/segcache.py):
        # - role="ingest" (+ --objstore): after every tier commit the
        #   SegmentPublisher mirrors adopted segments + dict dumps into
        #   the shared object store and swaps this shard's pointer.
        # - role="querier": a STATELESS read replica — no receiver, no
        #   decoders, no flusher, no local durability. It polls shard
        #   pointers, adopts published segments into RemoteTableTiers
        #   and serves sealed history; ingest shards answer only their
        #   live/unpublished rows via the publish-gen handshake.
        self.role = role if role in ("ingest", "querier") else "ingest"
        self.objstore_path = objstore
        # read-only alternate objstore roots (other replicas' stores):
        # fetches fail over to them when the primary copy is missing or
        # corrupt — blobs are immutable, so any copy is byte-identical
        self.objstore_mirrors = list(objstore_mirrors or [])
        self.segcache_max_bytes = max(1 << 20, int(segcache_max_bytes))
        self.publish_interval_s = publish_interval_s
        self.readtier_poll_s = readtier_poll_s
        self.objstore = None
        self.publisher = None
        self.segcache = None
        self.readtier = None
        self.partial_cache = None
        self._pub_stop = threading.Event()
        self._pub_thread: threading.Thread | None = None
        self._poll_stop = threading.Event()
        self._poll_thread: threading.Thread | None = None
        if self.role == "querier" and not objstore:
            raise ValueError("role=querier requires an --objstore path")
        # flow-log decode parallelism for THIS server instance; None
        # defers to the DF_INGEST_WORKERS env knob read at import time
        self.ingest_workers = ingest_workers
        # HA: with a lease (file path on a shared volume, OR a K8s Lease
        # object name for clusters without one), cluster SINGLETONS
        # (controller, rollups, janitor) run only on the elected leader;
        # every node serves ingest + query (reference: election.go:175)
        self.ha_lease_path = ha_lease_path
        self.ha_k8s_lease = ha_k8s_lease
        self.election = None
        # cluster federation: this node's shard identity + how to find
        # the seed (leader controller). Enabled by passing a seed and/or
        # an advertise address — a lone seed is a working 1-node cluster
        self.shard_id = shard_id
        self.cluster_seed = cluster_seed
        self.cluster_advertise = cluster_advertise
        self._cluster_on = (cluster_seed is not None
                            or cluster_advertise is not None)
        self._fanout_timeout_s = fanout_timeout_s
        self._fanout_hedge_delay_s = fanout_hedge_delay_s
        # replicated ingest: > 0 turns on the consistent-hash ring
        # (cluster/hashring.py). The elected leader (or the seed, when
        # no election is configured) builds/bumps the ring from the peer
        # directory; everyone else adopts it via the join exchange.
        self.replication = max(0, int(replication))
        self.membership = None
        self.fanout = None
        self.federation = None
        self._ring_stop = threading.Event()
        self._ring_thread: threading.Thread | None = None
        # persistent tiered storage (store/tiered.py): sealed chunks are
        # flushed into mmap-able columnar segments, and acks are released
        # only after the manifest commit that makes their rows durable
        self.storage = bool(storage and data_dir
                            and self.role == "ingest")
        self.flush_interval_s = flush_interval_s
        self.compact_interval_s = compact_interval_s
        self.scrub_interval_s = scrub_interval_s
        self.storage_max_bytes = max(0, int(storage_max_bytes))
        # a querier's tables are pure views over adopted remote
        # segments: no local persistence, no recovery — its data_dir
        # (when given) only roots the mmap segment cache, which lives
        # in a <data_dir>/segcache subdirectory it wipes on startup
        self._cache_root = data_dir if self.role == "querier" else None
        self.db = Database(
            data_dir=None if self.role == "querier" else data_dir,
            shard_id=shard_id, storage=self.storage)
        self.flusher = None
        self.compactor = None
        self.scrubber = None
        self.durability = None
        if self.storage:
            from deepflow_tpu.server.flusher import DurabilityGate
            self.durability = DurabilityGate()
        self.platform = PlatformInfoTable()
        from deepflow_tpu.server.platform_info import (PodIpIndex,
                                                       ResourceIndex)
        self.pod_index = PodIpIndex()  # K8s genesis resource model
        # IP-keyed universal-tag resolution (pods + services + nodes +
        # subnets) shared by every ingest decoder
        self.resources = ResourceIndex(self.pod_index)
        self.genesis = None            # started via start_genesis()
        # self-telemetry spine: per-hop frame ledger + stage heartbeats
        # + deadman detection (see deepflow_tpu/telemetry.py). One
        # Telemetry per Server instance — tests run several per process.
        from deepflow_tpu.telemetry import DeadmanDetector, Telemetry
        self.telemetry = Telemetry("server", enabled=selfmon)
        self.deadman = DeadmanDetector(self.telemetry,
                                       window_s=deadman_window_s)
        self._selfstats_interval_s = selfstats_interval_s
        self._selfstats_stop = threading.Event()
        self._selfstats_thread: threading.Thread | None = None
        self.receiver = Receiver(host=host, port=ingest_port,
                                 telemetry=self.telemetry)
        # closed-loop overload control (deepflow_tpu/qos): admission
        # fair-queuing in front of the decoders, a pressure controller
        # feeding per-tenant backpressure into Controller.Sync, and an
        # adaptive sampler the flow decoders consult under pressure.
        # qos_config: a QosConfig, a JSON path, or None (defaults +
        # DF_QOS_CONFIG). Querier replicas take no agent traffic.
        from deepflow_tpu.qos import Qos, QosConfig
        if isinstance(qos_config, str):
            qos_config = QosConfig.load(qos_config)
        elif qos_config is None:
            qos_config = QosConfig.load()
        self.qos = (Qos(qos_config, telemetry=self.telemetry)
                    if self.role == "ingest" else None)
        self.decoders = []
        self.dedup = None  # shared DedupWindow, built in start()
        self.controller = None
        if enable_controller:
            try:
                from deepflow_tpu.server.controller import Controller
            except ImportError as e:  # no grpcio: degrade, keep ingest+query
                log.warning("controller disabled (%s)", e)
            else:
                self.controller = Controller(
                    self.platform, host=host, port=sync_port,
                    pod_index=self.pod_index,
                    ring_provider=self._current_ring,
                    qos=self.qos)
        from deepflow_tpu.server.alerting import (AlertEngine,
                                                  StepRegressionDetector)
        from deepflow_tpu.server.exporters import ExporterManager
        from deepflow_tpu.server.tracetree import TraceTreeBuilder
        self.exporters = ExporterManager(telemetry=self.telemetry)
        self.alerts = AlertEngine(self.db)
        # step health: continuous regression watch over tpu_step_metrics
        self.step_detector = StepRegressionDetector(self.db)
        # ingest-time trace precompute (reference: tracetree_writer.go)
        self.trace_trees = TraceTreeBuilder(self.db)
        self.api = QuerierAPI(self.db, stats_provider=self._stats,
                              controller=self.controller,
                              exporters=self.exporters, alerts=self.alerts,
                              trace_trees=self.trace_trees,
                              telemetry=self.telemetry,
                              api_token=api_token,
                              shard_id=shard_id)
        self.http = QuerierHTTP(self.api,
                                host=query_host if query_host else host,
                                port=query_port)
        from deepflow_tpu.server.datasource import RollupJob
        from deepflow_tpu.server.janitor import Janitor
        self.rollup = RollupJob(self.db)
        self.janitor = Janitor(self.db, telemetry=self.telemetry,
                               tier_max_bytes=self.storage_max_bytes)
        # built after the api (rollup needs the db the api already holds)
        self.api.rollup = self.rollup
        self.api.storage_provider = self._storage_stats
        # standing-query registry (query/standing.py): shares the api's
        # QueryCache so standing folds and ad-hoc queries reuse the same
        # warm bucket partials (and the distributed partial cache)
        from deepflow_tpu.query.standing import StandingQueryRegistry
        self.standing = StandingQueryRegistry(
            self.db, self.api.query_cache, telemetry=self.telemetry,
            resolver=self.api._resolve_table)
        self.api.standing = self.standing
        self.alerts.standing = self.standing  # push-evaluated rules
        # /v1/health qos block + /v1/qos tenant table + dfctl qos
        self.api.qos = self.qos
        self.api.drop_attribution = self.receiver.drop_attribution
        self._started = False

    def start_genesis(self, api_base: str | None = None, token: str = "",
                      ca_path: str = "") -> bool:
        """Attach the K8s list-watch (in-cluster auto-config when args are
        empty). Returns False when no cluster is reachable."""
        from deepflow_tpu.server.genesis import K8sGenesis
        try:
            def _events(rows):
                self.db.table("event.event").append_rows(rows)

            self.genesis = K8sGenesis(self.pod_index, api_base=api_base,
                                      token=token, ca_path=ca_path,
                                      event_sink=_events,
                                      resources=self.resources,
                                      telemetry=self.telemetry).start()
            return True
        except (RuntimeError, ValueError) as e:
            # ValueError: https without ca (e.g. serviceaccount ca.crt
            # missing) — degrade to untagged flows, never abort server boot
            log.warning("k8s genesis not started: %s", e)
            return False

    def _stats(self) -> dict:
        return {
            "receiver": dict(self.receiver.stats),
            "decoders": {d.MSG_TYPE.name: dict(d.stats)
                         for d in self.decoders},
            "janitor": dict(self.janitor.stats),
            "flusher": (dict(self.flusher.stats)
                        if self.flusher is not None else None),
            "compactor": (dict(self.compactor.stats)
                          if self.compactor is not None else None),
            "scrubber": (dict(self.scrubber.stats)
                         if self.scrubber is not None else None),
            "genesis": (dict(self.genesis.stats)
                        if self.genesis is not None else None),
            "qos": (self.qos.snapshot()
                    if self.qos is not None else None),
            "drop_attribution": self.receiver.drop_attribution(),
        }

    def _flusher_backlog(self) -> float:
        """Durability-gate depth as a 0..1 pressure signal: acks the
        flusher has not yet released.  4096 pending seqs ≈ saturated.
        Sustained commit failure (full/faulty disk) saturates the
        signal directly — the gate may still be shallow right after the
        first failed flush, but nothing will drain it, so pressure must
        reach the agents before the spool does all the absorbing."""
        if self.durability is None:
            return 0.0
        depth = min(1.0, len(self.durability) / 4096.0)
        if self.flusher is not None and self.flusher.consec_errors:
            depth = max(depth, min(
                1.0, self.flusher.consec_errors / 3.0))
        return depth

    def _storage_stats(self) -> dict | None:
        """The /v1/health storage block: tier state + rollup horizons."""
        if self.db.tier_store is None:
            return None
        snap = self.db.tier_store.snapshot()
        snap["gate_pending"] = (len(self.durability)
                                if self.durability is not None else 0)
        if self.flusher is not None:
            snap["flush_consec_errors"] = self.flusher.consec_errors
        if self.scrubber is not None:
            snap["scrub"] = self.scrubber.snapshot()
        snap["rollup_horizons"] = {
            f"{fam}.{sfx}": wm
            for (fam, sfx), wm in self.rollup.horizons().items()}
        return snap

    def _selfstats_loop(self) -> None:
        """Write the server's OWN telemetry into deepflow_system — the
        analog of the reference's ckmonitor/self stats: the server has no
        agent in front of it, so it writes rows directly rather than
        shipping a StatsBatch to itself."""
        hb = self.telemetry.heartbeat(
            "selfstats", interval_hint_s=self._selfstats_interval_s)
        while not self._selfstats_stop.wait(self._selfstats_interval_s):
            hb.beat()
            try:
                self._write_selfstats()
            except Exception:
                log.exception("selfstats write failed")

    def _write_selfstats(self) -> None:
        tags = self.platform.tags_for(0)
        now = time.time_ns()
        rows = []
        for name, mtags, values in self.telemetry.stats_metrics():
            tag_json = json.dumps(mtags, sort_keys=True)
            for vname, v in values.items():
                rows.append({"time": now, "metric_name": name,
                             "tag_json": tag_json, "value_name": vname,
                             "value": v, **tags})
        if rows:
            self.db.table("deepflow_system.deepflow_system") \
                .append_rows(rows)

    def _current_ring(self):
        """The adopted replication ring, or None (handed as a zero-arg
        callable to decoders/controller built before membership is)."""
        m = self.membership
        return m.ring if m is not None else None

    def _ring_tick(self) -> None:
        """Leader-only ring maintenance: rebuild the ring whenever the
        peer DIRECTORY changes (join, address move, restart). A shard
        merely going silent does NOT bump the epoch — failover is the
        query-time claim shift to the surviving replica, not a
        rebalance. Fenced: the ring carries the election token, and
        adoption everywhere is forward-only on (token, epoch)."""
        m = self.membership
        if m is None:
            return
        if self.election is not None:
            if not self.election.is_leader:
                return
            token = self.election.token
        elif not m.is_seed:
            return
        else:
            token = 0
        from deepflow_tpu.cluster.hashring import HashRing
        snap = m.directory.snapshot()
        members = {p["shard_id"]: {"addr": p["addr"],
                                   "ingest": p.get("ingest_addr", "")}
                   for p in snap["peers"]
                   # queriers take no agent traffic: never ring owners
                   if p.get("role", "ingest") == "ingest"}
        ring = HashRing.build(m.ring, members, self.replication, token)
        if ring is not m.ring and m.publish_ring(ring):
            log.info("ring: epoch %d published (token %d, members %s)",
                     ring.epoch, ring.token, sorted(ring.members))

    def _ring_loop(self) -> None:
        while not self._ring_stop.wait(1.0):
            try:
                self._ring_tick()
            except Exception:
                log.exception("ring maintenance failed")

    def _ack_state_path(self) -> str | None:
        import os
        return (os.path.join(self.db.data_dir, "ack_state.json")
                if self.db.data_dir else None)

    def _load_ack_state(self) -> dict[int, int]:
        """Persisted per-agent contiguous-seq watermarks. Seeding BOTH the
        receiver's ack tracker and the decoders' dedup floors is what
        makes retransmits of pre-restart frames exactly-once: the rows
        are already in the (persisted) tables."""
        path = self._ack_state_path()
        if not path:
            return {}
        import os
        if not os.path.exists(path):
            return {}
        try:
            with open(path) as f:
                raw = json.load(f)
            return {int(k): int(v) for k, v in raw.items()}
        except (OSError, ValueError):
            # a torn/corrupt floors file is treated as ABSENT, never
            # fatal: floors restart from the tier manifest's copy (or
            # zero) and dedup re-absorbs the retransmits. Ledgered so
            # the recovery is visible, not silent.
            log.warning("ack state unreadable; starting fresh", exc_info=True)
            self.telemetry.hop("storage").account(
                emitted=1, dropped=1, reason="state_corrupt")
            return {}

    def _save_ack_state(self) -> None:
        # atomic: temp file + fsync + rename. A crash mid-write must
        # leave either the old state or the new — a truncated floors
        # file would poison dedup/ack seeding on the next boot.
        path = self._ack_state_path()
        if not path:
            return
        import os
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump({str(k): v for k, v in
                           self.receiver.seq_tracker.snapshot().items()}, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError:
            log.warning("ack state save failed", exc_info=True)
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def start(self) -> "Server":
        if self.db.data_dir:
            self.db.load()  # resume persisted tables
        # storage-scope chaos (DF_CHAOS tier_enospc/objstore_eio knobs)
        # hooks into the tier commit and blob publish paths; None (the
        # default) costs the hot paths one attribute check
        from deepflow_tpu.chaos import chaos_from_env
        chaos = chaos_from_env()
        if self.db.tier_store is not None:
            self.db.tier_store.chaos = chaos
            if self.db.tier_store.stats.get("manifest_corrupt"):
                # recovery met an unreadable MANIFEST.json and scavenged
                # the segment files instead — ledgered, never silent
                self.telemetry.hop("storage").account(
                    emitted=1, dropped=1, reason="state_corrupt")
        if self.objstore_path is not None:
            from deepflow_tpu.store.objstore import ObjStore
            self.objstore = ObjStore(self.objstore_path,
                                     mirrors=self.objstore_mirrors)
            self.objstore.chaos = chaos
        if self.role == "querier":
            self._start_readtier()
        else:
            self._start_ingest()
        self.http.start()
        if self._cluster_on:
            self._start_cluster()
        # both roles: queriers serve /v1/subscribe push traffic too
        self.standing.start()
        if self.role == "ingest":
            self.alerts.start()
            self.step_detector.start()
        self.deadman.start()
        if self.telemetry.enabled:
            self._selfstats_stop.clear()
            self._selfstats_thread = threading.Thread(
                target=self._selfstats_loop, name="df-selfstats",
                daemon=True)
            self._selfstats_thread.start()
        if self.ha_k8s_lease:
            import os as _os_e
            from deepflow_tpu.server.election import K8sLeaseElection
            try:
                self.election = K8sLeaseElection(
                    self.ha_k8s_lease,
                    namespace=_os_e.environ.get("POD_NAMESPACE",
                                                "default"),
                    on_elected=self._start_singletons,
                    on_deposed=self._stop_singletons).start()
            except (RuntimeError, ValueError) as e:
                log.warning("k8s lease election unavailable (%s); "
                            "running singletons locally", e)
                self._start_singletons()
        elif self.ha_lease_path:
            from deepflow_tpu.server.election import LeaderElection
            self.election = LeaderElection(
                self.ha_lease_path,
                on_elected=self._start_singletons,
                on_deposed=self._stop_singletons).start()
        else:
            self._start_singletons()
        import os as _os
        if _os.environ.get("KUBERNETES_SERVICE_HOST"):
            self.start_genesis()  # in-cluster: watch automatically
        self._started = True
        log.info("server up: role %s ingest :%d query :%d", self.role,
                 self.receiver.port if self.role == "ingest" else 0,
                 self.http.port)
        return self

    def _start_ingest(self) -> None:
        floors = self._load_ack_state()
        if self.storage:
            # the tier manifest carries floors committed ATOMICALLY with
            # the rows they cover — after a SIGKILL it is ahead of
            # ack_state.json (which only a clean stop writes). Max-wins
            # merge: both floors describe rows that are durable.
            for agent_id, contig in self.db.tier_store.ack_floors.items():
                if contig > floors.get(agent_id, -1):
                    floors[agent_id] = contig
        for agent_id, contig in floors.items():
            self.receiver.seq_tracker.seed(agent_id, contig)
        from deepflow_tpu.server.decoders import DedupWindow
        # ONE window shared by every decoder/worker: seq space is
        # per-agent, and a retransmit must dedup no matter which decoder
        # type it lands on
        self.dedup = DedupWindow(floors=floors)
        # SEQ_BASE announcements advance dedup floors too (receiver
        # handles those control frames inline)
        self.receiver.dedup = self.dedup
        # register all queues BEFORE listening: no drop window on restart
        from deepflow_tpu.server.decoders import PcapDecoder
        pairs = [
            (PcapDecoder, MessageType.PCAP),
            (ProfileDecoder, MessageType.PROFILE),
            (TpuSpanDecoder, MessageType.TPU_SPAN),
            (StepMetricsDecoder, MessageType.STEP_METRICS),
            (FlowLogDecoder, MessageType.L4_LOG),
            (FlowLogDecoder, MessageType.L7_LOG),
            (MetricsDecoder, MessageType.METRICS),
            (StatsDecoder, MessageType.DFSTATS),
            (EventDecoder, MessageType.EVENT),
        ]
        qos_on = self.qos is not None and self.qos.enabled
        if qos_on:
            # builds admission/pressure/sampler against the receiver's
            # deliver + ledger surfaces; must precede decoder
            # construction (flow decoders hold the sampler) and
            # receiver.start() (no un-admitted dispatch window)
            self.receiver.attach_qos(self.qos,
                                     flusher_backlog=self._flusher_backlog)
        for cls, mtype in pairs:
            kw = {}
            lanes = 1
            if cls is FlowLogDecoder:
                if qos_on:
                    kw["qos_sampler"] = self.qos.sampler
                workers = self.ingest_workers or FlowLogDecoder.WORKERS
                if self.ingest_workers:
                    kw["workers"] = self.ingest_workers
                # one lane queue per decode worker: each TCP connection
                # pins to a lane, so N agents decode on N workers and a
                # single hot agent cannot serialize the native path
                lanes = workers
            q = self.receiver.register(mtype, lanes=lanes)
            d = cls(q, self.db, self.platform, exporters=self.exporters,
                    pod_index=self.pod_index, resources=self.resources,
                    gpid_table=(self.controller.gpids
                                if self.controller else None),
                    telemetry=self.telemetry, dedup=self.dedup,
                    seq_tracker=self.receiver.seq_tracker,
                    ring=self._current_ring,
                    durability=self.durability, **kw)
            d.MSG_TYPE = mtype  # FlowLogDecoder serves two types
            self.decoders.append(d.start())
        if self.storage:
            from deepflow_tpu.server.flusher import Compactor, Flusher
            self.flusher = Flusher(self.db, gate=self.durability,
                                   seq_tracker=self.receiver.seq_tracker,
                                   interval_s=self.flush_interval_s,
                                   telemetry=self.telemetry)
            self.flusher.seed_floors(floors)
            self.flusher.start()
            if self.compact_interval_s > 0:
                self.compactor = Compactor(
                    self.db, interval_s=self.compact_interval_s,
                    telemetry=self.telemetry).start()
            if self.scrub_interval_s > 0:
                from deepflow_tpu.store.scrub import Scrubber
                self.scrubber = Scrubber(
                    self.db, objstore=self.objstore,
                    shard_id=self.shard_id,
                    interval_s=self.scrub_interval_s,
                    telemetry=self.telemetry).start()
                self.api.scrubber = self.scrubber
        if qos_on:
            self.qos.start()
        self.receiver.start()
        if self.objstore is not None and self.storage:
            # publish sealed state to the shared store so stateless
            # querier replicas can adopt it (see store/objstore.py)
            from deepflow_tpu.store.objstore import SegmentPublisher
            self.publisher = SegmentPublisher(self.objstore,
                                              self.shard_id)
            self.api.publisher = self.publisher
            self._pub_stop.clear()
            self._pub_thread = threading.Thread(
                target=self._publish_loop, name="df-publish",
                daemon=True)
            self._pub_thread.start()

    def _start_readtier(self) -> None:
        """Querier role: no receiver/decoders/flusher. The node adopts
        published segments from the object store into a byte-budgeted
        local cache and serves sealed history over them."""
        import os
        import tempfile
        from deepflow_tpu.store.segcache import ReadTier, SegmentCache
        # a dedicated subdirectory, NEVER data_dir itself: the cache
        # wipes its root on startup, and a --data-dir pointing at an
        # existing tier (e.g. an ingest node's) must survive a querier
        # started against it by mistake
        root = (os.path.join(self._cache_root, "segcache")
                if self._cache_root
                else tempfile.mkdtemp(prefix="df-segcache-"))
        self.segcache = SegmentCache(
            root, self.objstore, max_bytes=self.segcache_max_bytes,
            telemetry=self.telemetry)
        self.readtier = ReadTier(self.db, self.objstore, self.segcache,
                                 shard_id=self.shard_id)
        self.api.readtier = self.readtier
        if self.scrub_interval_s > 0:
            # a querier scrubs its CACHED copies: a corrupt one is
            # discarded and transparently re-fetched on the next pin
            from deepflow_tpu.store.scrub import Scrubber
            self.scrubber = Scrubber(
                self.db, segcache=self.segcache,
                shard_id=self.shard_id,
                interval_s=self.scrub_interval_s,
                telemetry=self.telemetry).start()
            self.api.scrubber = self.scrubber
        try:
            self.readtier.poll()  # first adoption before serving
        except Exception:
            log.exception("initial read-tier poll failed")
        self._poll_stop.clear()
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name="df-readtier", daemon=True)
        self._poll_thread.start()

    def _publish_loop(self) -> None:
        while not self._pub_stop.wait(self.publish_interval_s):
            try:
                self.publisher.maybe_publish(self.db.tier_store)
            except Exception:
                log.exception("segment publish failed")

    def _poll_loop(self) -> None:
        while not self._poll_stop.wait(self.readtier_poll_s):
            try:
                self.readtier.poll()
            except Exception:
                log.exception("read-tier poll failed")

    def _start_cluster(self) -> None:
        # after http.start(): with --query-port 0 the advertise
        # address needs the REAL bound port
        from deepflow_tpu.cluster.federation import FederationCoordinator
        from deepflow_tpu.cluster.membership import ClusterMembership
        from deepflow_tpu.cluster.remote import FanOut
        adv = (self.cluster_advertise
               or f"127.0.0.1:{self.http.port}")
        self.membership = ClusterMembership(
            self.shard_id, adv, seed=self.cluster_seed,
            role=self.role, telemetry=self.telemetry)
        if self.role == "ingest":
            # agents ship frames to the RECEIVER port; peers gossip it
            # so the ring can hand agent-facing ingest addrs around.
            # Queriers take no agent traffic and stay out of the ring.
            self.membership.ingest_addr = (
                f"{adv.rsplit(':', 1)[0]}:{self.receiver.port}")
        self.membership.start()
        self.fanout = FanOut(
            telemetry=self.telemetry,
            timeout_s=self._fanout_timeout_s,
            hedge_delay_s=self._fanout_hedge_delay_s,
            api_token=self.api.api_token or None)
        self.federation = FederationCoordinator(
            self.db, self.membership, self.fanout,
            shard_id=self.shard_id)
        self.api.membership = self.membership
        self.api.federation = self.federation
        # federated standing refreshes ride the if_state machinery:
        # only shards whose change token moved recompute
        self.standing.federation = self.federation
        if self.readtier is not None:
            # read-tier coordinator: freeze adopted snapshots across the
            # scatter, send the publish-gen handshake, and join the
            # cluster-wide partial-aggregate cache
            self.federation.readtier = self.readtier
            self.federation.query_cache = self.api.query_cache
            from deepflow_tpu.cluster.partialcache import PartialCache
            self.partial_cache = PartialCache(
                self.api.query_cache, self.membership,
                self.federation.dict_sync, self.db,
                shard_id=self.shard_id, telemetry=self.telemetry,
                api_token=self.api.api_token or None)
            self.partial_cache.readtier = self.readtier
            self.api.partial_cache = self.partial_cache
        if self.replication > 0 and self.role == "ingest":
            self._ring_stop.clear()
            self._ring_thread = threading.Thread(
                target=self._ring_loop, name="df-ring", daemon=True)
            self._ring_thread.start()

    def _start_singletons(self) -> None:
        """Leader-only components (no-op when already running)."""
        if self.role == "ingest" and not self.rollup.running():
            self.rollup.start()
        if not self.janitor.running():
            self.janitor.start()
        if self.controller and not self.controller.running():
            self.controller.start()

    def _stop_singletons(self) -> None:
        self.rollup.stop()
        self.janitor.stop()
        if self.controller:
            self.controller.stop()

    def stop(self) -> None:
        if self.election is not None:
            self.election.stop()
            self.election = None
        if self.genesis is not None:
            self.genesis.stop()
            self.genesis = None
        if not self._started:
            return
        self.deadman.stop()
        self._pub_stop.set()
        if self._pub_thread is not None:
            self._pub_thread.join(timeout=2.0)
            self._pub_thread = None
        self._poll_stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=2.0)
            self._poll_thread = None
        self._ring_stop.set()
        if self._ring_thread is not None:
            self._ring_thread.join(timeout=2.0)
            self._ring_thread = None
        if self.membership is not None:
            self.membership.stop()
        if self.fanout is not None:
            self.fanout.close()
        self._selfstats_stop.set()
        if self._selfstats_thread is not None:
            self._selfstats_thread.join(timeout=2.0)
            self._selfstats_thread = None
        if self.role == "ingest":
            self.receiver.stop()
        if self.qos is not None:
            # after receiver.stop() (no new submissions), before the
            # decoder drain: parked admission frames flush into the
            # decoder queues so the drain below commits them
            self.qos.stop()
        for d in self.decoders:
            d.stop()  # joins workers, then drains the queue: acked
            # frames must reach the tables before the db persists
            if hasattr(d, "flush"):
                d.flush()  # stateful reducers drain pending windows
                # BEFORE the db persists (the file_agg tail otherwise
                # vanishes on every restart)
        if self.scrubber is not None:
            # before the final flush: a quarantine is a manifest commit
            # too — stop the scrubber racing the shutdown renames
            self.scrubber.stop()
            self.scrubber = None
        if self.compactor is not None:
            # before the final flush: a mid-commit compaction and the
            # flush both rename the manifest; stop the race first
            self.compactor.stop()
            self.compactor = None
        if self.flusher is not None:
            # after the decoder drain: the final flush commits everything
            # they wrote (and parked) and releases the last gated seqs,
            # so the ack state written below matches durable rows
            self.flusher.stop()
            self.flusher = None
        if self.publisher is not None and self.db.tier_store is not None:
            # after the final flush: publish whatever it sealed so
            # queriers see the full history across a clean restart
            try:
                self.publisher.maybe_publish(self.db.tier_store)
            except Exception:
                log.exception("final segment publish failed")
        # persist ack watermarks AFTER the drain: every acked frame is
        # now in a table, so seeding dedup floors from this state on the
        # next start cannot mask an undecoded frame
        self._save_ack_state()
        # before http.stop(): closing every subscriber unblocks any SSE
        # handler thread parked in a long poll
        self.standing.stop()
        self.http.stop()
        self._stop_singletons()
        self.alerts.stop()
        self.step_detector.stop()
        self.exporters.stop()
        try:
            for err in self.db.flush():
                log.error("flush: %s", err)
            if self.db.data_dir:
                self.db.save()
        finally:
            self._started = False

    @property
    def ingest_port(self) -> int:
        return self.receiver.port

    @property
    def query_port(self) -> int:
        return self.http.port

    def wait_for_rows(self, table: str, n: int, timeout: float = 5.0) -> bool:
        """Test/ops helper: block until a table holds >= n rows."""
        deadline = time.monotonic() + timeout
        t = self.db.table(table)
        while time.monotonic() < deadline:
            if len(t) >= n:
                return True
            time.sleep(0.02)
        return len(t) >= n


def main() -> None:
    import argparse
    parser = argparse.ArgumentParser(description="deepflow-tpu server")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--ingest-port", type=int, default=20033)
    # querier default: LOCALHOST. The query surface carries control-plane
    # mutations (repo upload, agent exec); exposing it is an explicit
    # opt-in (--query-host 0.0.0.0) best paired with --api-token.
    # See docs/SECURITY.md.
    parser.add_argument("--query-host", default="127.0.0.1",
                        help="querier bind address (default localhost; "
                             "set 0.0.0.0 to expose, ideally with "
                             "--api-token)")
    parser.add_argument("--query-port", type=int, default=20416)
    parser.add_argument("--api-token", default=None,
                        help="shared token gating /v1/repo upload and the "
                             "OTA upgrade exec (default: $DF_API_TOKEN)")
    parser.add_argument("--deadman-window-s", type=float, default=15.0,
                        help="flag a stage wedged after this many seconds "
                             "without a heartbeat")
    parser.add_argument("--sync-port", type=int, default=20035)
    parser.add_argument("--shard-id", type=int, default=0,
                        help="this node's cluster shard identity "
                             "(tags ingested rows; 0 = standalone)")
    parser.add_argument("--cluster-seed", default=None,
                        help="seed node addr host:query_port to join "
                             "(the leader controller's querier)")
    parser.add_argument("--advertise", default=None,
                        help="addr other shards reach THIS querier at "
                             "(default 127.0.0.1:<query-port>)")
    parser.add_argument("--fanout-timeout-s", type=float, default=5.0,
                        help="per-shard scatter-gather call deadline; "
                             "slower shards degrade to missing_shards")
    parser.add_argument("--replication", type=int, default=0,
                        help="replication factor R for ingested HIGH/MID "
                             "frames (0 = off): each agent ships to R "
                             "ring owners; queries stay exact through "
                             "R-1 simultaneous shard failures")
    parser.add_argument("--data-dir", default=None)
    parser.add_argument("--storage", action="store_true",
                        help="persistent tiered storage: flush sealed "
                             "chunks into on-disk columnar segments "
                             "under <data-dir>/segments and release "
                             "ingest acks only after the commit that "
                             "makes their rows durable")
    parser.add_argument("--flush-interval-s", type=float, default=1.0,
                        help="tier flush cadence (storage mode)")
    parser.add_argument("--compact-interval-s", type=float, default=60.0,
                        help="tier compaction cadence (storage mode): "
                             "merge small sealed segments into sorted "
                             "format-v2 runs; 0 disables")
    parser.add_argument("--scrub-interval-s", type=float, default=30.0,
                        help="background integrity-scrub cadence "
                             "(storage/querier modes): verify segment "
                             "block checksums, quarantine + repair "
                             "corrupt segments; 0 disables")
    parser.add_argument("--storage-max-mb", type=int, default=0,
                        help="on-disk tier size budget per node; the "
                             "janitor evicts oldest segments past it "
                             "(0 = TTL-only eviction)")
    parser.add_argument("--role", default="ingest",
                        choices=("ingest", "querier"),
                        help="ingest: full write path (receiver + "
                             "decoders + flusher); querier: stateless "
                             "read replica serving sealed history "
                             "fetched on demand from --objstore")
    parser.add_argument("--objstore", default=None,
                        help="shared object-store directory. Ingest "
                             "nodes publish sealed segments + manifest "
                             "pointers there; queriers adopt them "
                             "(required for --role querier)")
    parser.add_argument("--objstore-mirror", action="append",
                        default=None, metavar="DIR",
                        help="read-only alternate object-store root "
                             "(repeatable): fetches fail over to it "
                             "when the primary copy is missing or "
                             "fails checksum verification")
    parser.add_argument("--segcache-max-mb", type=int, default=256,
                        help="querier local segment-cache byte budget; "
                             "least-recently-used segments past it are "
                             "evicted (refetched on demand)")
    parser.add_argument("--publish-interval-s", type=float, default=2.0,
                        help="ingest publish cadence to --objstore")
    parser.add_argument("--readtier-poll-s", type=float, default=2.0,
                        help="querier manifest-pointer poll cadence")
    parser.add_argument("--ha-lease", default=None,
                        help="shared-volume lease FILE for leader election")
    parser.add_argument("--ha-k8s-lease", default=None,
                        help="K8s Lease object name for leader election "
                             "(no shared volume needed)")
    parser.add_argument("--qos-config", default=None,
                        help="JSON tenant-QoS policy (weights, "
                             "rate_fps quotas, pressure thresholds); "
                             "default $DF_QOS_CONFIG or built-ins. "
                             "DF_NO_QOS=1 disables the subsystem")
    parser.add_argument("--no-controller", action="store_true")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args()
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    server = Server(host=args.host, ingest_port=args.ingest_port,
                    query_port=args.query_port, sync_port=args.sync_port,
                    query_host=args.query_host,
                    data_dir=args.data_dir,
                    ha_lease_path=args.ha_lease,
                    ha_k8s_lease=args.ha_k8s_lease,
                    api_token=args.api_token,
                    deadman_window_s=args.deadman_window_s,
                    shard_id=args.shard_id,
                    cluster_seed=args.cluster_seed,
                    cluster_advertise=args.advertise,
                    fanout_timeout_s=args.fanout_timeout_s,
                    replication=args.replication,
                    storage=args.storage,
                    flush_interval_s=args.flush_interval_s,
                    compact_interval_s=args.compact_interval_s,
                    scrub_interval_s=args.scrub_interval_s,
                    storage_max_bytes=args.storage_max_mb << 20,
                    role=args.role, objstore=args.objstore,
                    objstore_mirrors=args.objstore_mirror,
                    segcache_max_bytes=args.segcache_max_mb << 20,
                    publish_interval_s=args.publish_interval_s,
                    readtier_poll_s=args.readtier_poll_s,
                    qos_config=args.qos_config,
                    enable_controller=(not args.no_controller
                                       and args.role != "querier")).start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
