"""Prometheus SmartEncoding: cluster-wide metric/label-set id allocation.

Reference analog: server/controller/prometheus/ (the label/metric id
allocator served to agents+ingesters via message/trident.proto:11
GetPrometheusLabelIDs). Redesign around the embedded store: the unit of
encoding is the SERIES label set (one canonical json string), not each
label name/value pair — our columnar dictionaries already dedup strings
node-locally; what the control plane adds is that every ingest node gets
the SAME id for the same series, so rows from different nodes join.

Three pieces:
- PromEncoder: the authoritative allocator (lives in the controller).
- GrpcPromEncoderClient: remote ingest nodes' view, with a local cache so
  steady-state ingest makes no RPCs.
- Both expose encode(metric_names, label_sets) -> (metric_ids, set_ids).
"""

from __future__ import annotations

import logging
import threading

from deepflow_tpu.proto import pb

log = logging.getLogger("df.prom-encoder")


class PromEncoder:
    """Authoritative id allocator (controller-side)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metric_ids: dict[str, int] = {}
        self._set_ids: dict[str, int] = {}
        self._next_metric = 1
        self._next_set = 1

    def encode(self, metric_names: list[str],
               label_sets: list[str]) -> tuple[list[int], list[int]]:
        with self._lock:
            mids = []
            for name in metric_names:
                mid = self._metric_ids.get(name)
                if mid is None:
                    mid = self._metric_ids[name] = self._next_metric
                    self._next_metric += 1
                mids.append(mid)
            sids = []
            for ls in label_sets:
                sid = self._set_ids.get(ls)
                if sid is None:
                    sid = self._set_ids[ls] = self._next_set
                    self._next_set += 1
                sids.append(sid)
            return mids, sids

    def seed(self, metric_ids: dict[str, int],
             set_ids: dict[str, int]) -> None:
        """Restore allocator state from persisted tables at boot — the ids
        on disk are forever; a restart must never re-allocate them."""
        with self._lock:
            self._metric_ids.update(metric_ids)
            self._set_ids.update(set_ids)
            if self._metric_ids:
                self._next_metric = max(self._next_metric,
                                        max(self._metric_ids.values()) + 1)
            if self._set_ids:
                self._next_set = max(self._next_set,
                                     max(self._set_ids.values()) + 1)

    # gRPC handler body (wired by the controller)
    def handle(self, request: pb.PromEncodeRequest) -> pb.PromEncodeResponse:
        mids, sids = self.encode(list(request.metric_names),
                                 list(request.label_sets))
        resp = pb.PromEncodeResponse()
        resp.metric_ids.extend(mids)
        resp.label_set_ids.extend(sids)
        return resp


class GrpcPromEncoderClient:
    """Ingest-node view of the controller allocator, with a local cache
    (ids are immutable once assigned, so the cache never invalidates)."""

    METHOD = "/deepflow_tpu.Synchronizer/PromEncode"

    def __init__(self, channel) -> None:
        self._stub = channel.unary_unary(
            self.METHOD,
            request_serializer=pb.PromEncodeRequest.SerializeToString,
            response_deserializer=pb.PromEncodeResponse.FromString)
        self._lock = threading.Lock()
        self._metric_cache: dict[str, int] = {}
        self._set_cache: dict[str, int] = {}

    def encode(self, metric_names: list[str],
               label_sets: list[str]) -> tuple[list[int], list[int]]:
        with self._lock:
            miss_names = [n for n in set(metric_names)
                          if n not in self._metric_cache]
            miss_sets = [s for s in set(label_sets)
                         if s not in self._set_cache]
        if miss_names or miss_sets:
            req = pb.PromEncodeRequest()
            req.metric_names.extend(miss_names)
            req.label_sets.extend(miss_sets)
            resp = self._stub(req, timeout=10)
            with self._lock:
                for n, i in zip(miss_names, resp.metric_ids):
                    self._metric_cache[n] = i
                for s, i in zip(miss_sets, resp.label_set_ids):
                    self._set_cache[s] = i
        with self._lock:
            return ([self._metric_cache[n] for n in metric_names],
                    [self._set_cache[s] for s in label_sets])
