"""Alert engine: periodic DF-SQL/PromQL conditions -> alert events.

Reference analog: message/alert_event.proto + the alert-event family of
ingester/event (alert_event_writer.go). Rules evaluate on a timer; a firing
rule writes an event.event row (event_type="alert") and optionally POSTs a
webhook. Hysteresis: one event per state transition, not per tick.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request

from deepflow_tpu.query import engine as qengine
from deepflow_tpu.store.db import Database

log = logging.getLogger("df.alerting")

_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}


class AlertRule:
    def __init__(self, name: str, db_name: str, sql: str, op: str,
                 threshold: float, severity: str = "warning",
                 interval_s: float = 15.0, webhook: str = "") -> None:
        if op not in _OPS:
            raise ValueError(f"bad op {op!r}; use one of {sorted(_OPS)}")
        self.name = name
        self.db_name = db_name
        self.sql = sql
        self.op = op
        self.threshold = float(threshold)
        self.severity = severity
        self.interval_s = interval_s
        self.webhook = webhook
        self.firing = False
        self.last_value: float | None = None
        self.last_eval_ns = 0
        self.in_error = False          # rule_error hysteresis
        self.standing_name: str | None = None  # push-evaluated when set

    def to_dict(self) -> dict:
        return {"name": self.name, "db": self.db_name, "sql": self.sql,
                "op": self.op, "threshold": self.threshold,
                "severity": self.severity, "interval_s": self.interval_s,
                "firing": self.firing, "last_value": self.last_value}


class AlertEngine:
    def __init__(self, db: Database, api=None) -> None:
        self.db = db
        self.api = api  # QuerierAPI for table resolution (optional)
        self.rules: dict[str, AlertRule] = {}
        self._lock = threading.Lock()
        # one eval at a time per engine: the push hook (standing-query
        # refresher thread) and the timer loop both transition firing
        # state; without this a breach could double-emit
        self._eval_lock = threading.Lock()
        self._standing = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.stats = {"evals": 0, "fired": 0, "resolved": 0, "errors": 0,
                      "push_evals": 0, "rule_errors": 0}

    # -- standing-query integration -------------------------------------------

    @property
    def standing(self):
        return self._standing

    @standing.setter
    def standing(self, registry) -> None:
        """Attach a StandingQueryRegistry: rules become standing queries
        (``alert:<name>``) evaluated the moment an update is published,
        instead of re-running their SQL on the poll timer."""
        self._standing = registry
        if registry is not None:
            registry.hooks.append(self._on_standing_update)
            with self._lock:
                rules = list(self.rules.values())
            for rule in rules:
                self._register_standing(rule)

    def _register_standing(self, rule: AlertRule) -> None:
        reg = self._standing
        if reg is None:
            return
        try:
            table, _sel = self._resolve_table(rule)
            reg.register(rule.sql, name=f"alert:{rule.name}",
                         table=table.name)
            rule.standing_name = f"alert:{rule.name}"
        except Exception as e:
            # not standing-capable (or registry down): the timer loop
            # keeps evaluating this rule the classic way
            rule.standing_name = None
            log.debug("standing registration failed for %s: %s",
                      rule.name, e)

    def _on_standing_update(self, name: str, update: dict) -> None:
        """Registry push hook. Runs on the refresher thread while the
        standing query's own lock is held — so the value comes from the
        update payload, never from registry.value_of()."""
        if not name.startswith("alert:"):
            return
        with self._lock:
            rule = self.rules.get(name[len("alert:"):])
        if rule is None:
            return
        rows = update.get("rows") or []
        value = rows[0][0] if rows and rows[0] else 0.0
        if not isinstance(value, (int, float)):
            return
        self.stats["push_evals"] += 1
        try:
            self.eval_rule(rule, value=float(value))
        except Exception as e:
            self._rule_error(rule, e)

    # -- rule management ------------------------------------------------------

    def upsert(self, spec: dict) -> AlertRule:
        rule = AlertRule(
            name=str(spec["name"]),
            db_name=str(spec.get("db", "")),
            sql=str(spec["sql"]),
            op=str(spec.get("op", ">")),
            threshold=float(spec.get("threshold", 0)),
            severity=str(spec.get("severity", "warning")),
            interval_s=float(spec.get("interval_s", 15.0)),
            webhook=str(spec.get("webhook", "")))
        # dry-run the query so bad rules are rejected at submit time
        self._query_value(rule)
        with self._lock:
            prev = self.rules.get(rule.name)
            if prev is not None:
                # editing a rule must not reset its firing state — a
                # re-upsert while firing would re-emit the alert event
                rule.firing = prev.firing
                rule.last_value = prev.last_value
                rule.last_eval_ns = prev.last_eval_ns
                rule.in_error = prev.in_error
            self.rules[rule.name] = rule
        self._register_standing(rule)
        return rule

    def delete(self, name: str) -> bool:
        with self._lock:
            rule = self.rules.pop(name, None)
        if rule is not None and rule.standing_name \
                and self._standing is not None:
            self._standing.unregister(rule.standing_name)
        return rule is not None

    def list(self) -> list[dict]:
        with self._lock:
            return [r.to_dict() for r in self.rules.values()]

    # -- evaluation -----------------------------------------------------------

    def _resolve_table(self, rule: AlertRule):
        from deepflow_tpu.query import sql as qsql
        select = qsql.parse(rule.sql)
        candidates = [select.table, f"{select.table}.1s"]
        if rule.db_name:
            candidates = [f"{rule.db_name}.{select.table}",
                          f"{rule.db_name}.{select.table}.1s"] + candidates
        for cand in candidates:
            try:
                return self.db.table(cand), select
            except KeyError:
                continue
        raise qengine.QueryError(f"no such table {select.table!r}")

    def _query_value(self, rule: AlertRule) -> float:
        table, select = self._resolve_table(rule)
        res = qengine.execute(table, select)
        if not res.values or not res.values[0]:
            return 0.0
        v = res.values[0][0]
        if not isinstance(v, (int, float)):
            raise qengine.QueryError(
                f"alert query must yield a number, got {v!r}")
        return float(v)

    def eval_rule(self, rule: AlertRule, now_ns: int | None = None,
                  value: float | None = None) -> None:
        """Evaluate one rule. ``value=None`` re-runs the rule's SQL
        from scratch (submit-time dry-runs, direct calls); push and
        timer paths pass the standing query's maintained value."""
        now = now_ns if now_ns is not None else time.time_ns()
        if value is None:
            value = self._query_value(rule)
        with self._eval_lock:
            rule.last_value = value
            rule.last_eval_ns = now
            rule.in_error = False
            self.stats["evals"] += 1
            breach = _OPS[rule.op](value, rule.threshold)
            if breach and not rule.firing:
                rule.firing = True
                self.stats["fired"] += 1
                self._emit(rule, "alert", value, now)
            elif not breach and rule.firing:
                rule.firing = False
                self.stats["resolved"] += 1
                self._emit(rule, "alert-resolved", value, now)

    def _rule_error(self, rule: AlertRule, err: Exception,
                    now_ns: int | None = None) -> None:
        """A failed evaluation becomes a visible event.event row —
        one per error transition (hysteresis like firing), so a broken
        rule can't storm the events table."""
        self.stats["errors"] += 1
        log.exception("alert eval failed: %s", rule.name)
        if rule.in_error:
            return
        rule.in_error = True
        self.stats["rule_errors"] += 1
        try:
            self.db.table("event.event").append_rows([{
                "time": now_ns if now_ns is not None else time.time_ns(),
                "event_type": "rule_error",
                "resource_type": "alert-rule",
                "resource_name": rule.name,
                "description": f"{type(err).__name__}: {err}",
                "attrs": json.dumps({"severity": rule.severity,
                                     "sql": rule.sql}),
            }])
        except Exception:
            log.debug("rule_error event append failed", exc_info=True)

    def _emit(self, rule: AlertRule, etype: str, value: float,
              now_ns: int) -> None:
        self.db.table("event.event").append_rows([{
            "time": now_ns,
            "event_type": etype,
            "resource_type": "alert-rule",
            "resource_name": rule.name,
            "description": (f"{rule.sql} -> {value:.6g} "
                            f"{rule.op} {rule.threshold:.6g}"),
            "attrs": json.dumps({"severity": rule.severity,
                                 "value": value}),
        }])
        log.warning("%s: %s (value=%.6g %s %.6g)", etype, rule.name, value,
                    rule.op, rule.threshold)
        if rule.webhook:
            try:
                req = urllib.request.Request(
                    rule.webhook,
                    data=json.dumps({
                        "rule": rule.name, "type": etype, "value": value,
                        "severity": rule.severity,
                        "threshold": rule.threshold}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=5):
                    pass
            except Exception as e:
                log.debug("webhook failed: %s", e)

    # -- loop -----------------------------------------------------------------

    def start(self) -> "AlertEngine":
        self._thread = threading.Thread(
            target=self._run, name="df-alerting", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.wait(1.0):
            now = time.time_ns()
            with self._lock:
                due = [r for r in self.rules.values()
                       if now - r.last_eval_ns >= r.interval_s * 1e9]
            for rule in due:
                try:
                    value = None
                    if rule.standing_name and self._standing is not None:
                        # push covers transitions; the timer tick reads
                        # the maintained value (exact while the change
                        # token holds still) instead of re-querying
                        value = self._standing.value_of(rule.standing_name)
                    self.eval_rule(rule, now, value=value)
                except Exception as e:
                    self._rule_error(rule, e, now)

    def snapshot(self) -> dict:
        """The /v1/health alerting block."""
        with self._lock:
            rules = list(self.rules.values())
        return {"rules": len(rules),
                "firing": sorted(r.name for r in rules if r.firing),
                "errored": sorted(r.name for r in rules if r.in_error),
                "push": self._standing is not None,
                "stats": dict(self.stats)}


_STEP_SQL = ("SELECT time, end_ns, latency_ns, run_id, step, job, "
             "device_count, device_skew_ns, compute_ns, collective_ns, "
             "straggler_device, straggler_lag_ns, top_hlos, host "
             "FROM tpu_step_metrics")


class StepRegressionDetector:
    """Streaming EWMA+MAD regression detector over per-step latency.

    Polls profile.tpu_step_metrics, folds host partials into pod-level
    rollups (stephealth.merge_host_partials), and feeds each job's step
    sequence through an EwmaMad scorer. A step past the threshold emits a
    `step_regression` alert event CARRYING THE ATTRIBUTION VERDICT —
    compute vs collective vs skew, the straggler device/host, and the
    dominant HLOs diffed against the rolling baseline of recent healthy
    steps. Hysteresis like AlertRule: one event per state transition.

    Completion rule: a (job, run_id, step) rollup may still be growing —
    other hosts' partials can trail. It is scored only once a NEWER
    run_id exists for the job, or its record count held stable across a
    full poll; until then it waits, unscored, so a half-arrived step
    never reads as a pod-wide regression.
    """

    def __init__(self, db: Database, interval_s: float = 1.0,
                 alpha: float | None = None, k: float | None = None,
                 min_steps: int | None = None,
                 severity: str = "warning") -> None:
        from deepflow_tpu.server import stephealth
        self.db = db
        self.interval_s = interval_s
        self.severity = severity
        self._sh = stephealth
        self._kw = {}
        if alpha is not None:
            self._kw["alpha"] = alpha
        if k is not None:
            self._kw["k"] = k
        if min_steps is not None:
            self._kw["min_steps"] = min_steps
        self._scorers: dict[str, object] = {}       # job -> EwmaMad
        self._processed: dict[str, set] = {}        # job -> {(run, step)}
        self._counts: dict[tuple, int] = {}         # key -> records seen
        self._firing: dict[str, bool] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.stats = {"polls": 0, "steps_scored": 0, "fired": 0,
                      "resolved": 0, "errors": 0}

    # -- scoring --------------------------------------------------------------

    def _rollups(self) -> list[dict]:
        table = self.db.table("profile.tpu_step_metrics")
        if not len(table):
            return []
        res = qengine.execute(table, _STEP_SQL)
        rows = [dict(zip(res.columns, vals)) for vals in res.values]
        return self._sh.merge_host_partials(rows)

    def poll(self, now_ns: int | None = None) -> list[dict]:
        """One detector pass; returns the alert payloads emitted (tests
        and steps-check call this directly instead of sleeping)."""
        now = now_ns if now_ns is not None else time.time_ns()
        emitted: list[dict] = []
        with self._lock:
            self.stats["polls"] += 1
            try:
                rollups = self._rollups()
            except Exception:
                self.stats["errors"] += 1
                log.exception("step detector scan failed")
                return emitted
            by_job: dict[str, list[dict]] = {}
            for r in rollups:
                by_job.setdefault(r["job"], []).append(r)
            new_counts: dict[tuple, int] = {}
            for job, steps in by_job.items():
                done = self._processed.setdefault(job, set())
                max_run = max(s["run_id"] for s in steps)
                for s in steps:
                    key = (job, s["run_id"], s["step"])
                    if (s["run_id"], s["step"]) in done:
                        continue
                    stable = self._counts.get(key) == s["records"]
                    if s["run_id"] >= max_run and not stable:
                        new_counts[key] = s["records"]
                        continue  # may still be growing; revisit
                    done.add((s["run_id"], s["step"]))
                    emitted.extend(self._score(job, s, now))
            self._counts = new_counts
        return emitted

    def _score(self, job: str, rollup: dict, now_ns: int) -> list[dict]:
        sc = self._scorers.get(job)
        if sc is None:
            self._scorers[job] = sc = self._sh.EwmaMad(**self._kw)
        baseline = sc.baseline()
        regressed = sc.feed(rollup)
        self.stats["steps_scored"] += 1
        out = []
        if regressed and not self._firing.get(job):
            self._firing[job] = True
            self.stats["fired"] += 1
            att = self._sh.attribute(rollup, baseline)
            out.append(self._emit(job, rollup, att, "alert", now_ns))
        elif not regressed and self._firing.get(job):
            self._firing[job] = False
            self.stats["resolved"] += 1
            out.append(self._emit(job, rollup, None, "alert-resolved",
                                  now_ns))
        return out

    def _emit(self, job: str, rollup: dict, attribution: dict | None,
              etype: str, now_ns: int) -> dict:
        if attribution:
            dom = attribution["dominant_hlos"]
            straggler = (f"{attribution['straggler_host']}:"
                         f"{attribution['straggler_device']}"
                         if attribution["straggler_host"]
                         else str(attribution["straggler_device"]))
            desc = (f"job {job or '?'} step {rollup['step']} "
                    f"(run {rollup['run_id']}): latency "
                    f"{rollup['latency_ns']}ns vs baseline "
                    f"{attribution['baseline_latency_ns']}ns, "
                    f"verdict={attribution['verdict']}, "
                    f"straggler={straggler}"
                    + (f", hlo={dom[0]['hlo_op']}" if dom else ""))
        else:
            desc = (f"job {job or '?'} step {rollup['step']} "
                    f"(run {rollup['run_id']}): latency back under "
                    f"threshold ({rollup['latency_ns']}ns)")
        attrs = {"severity": self.severity, "job": job,
                 "run_id": rollup["run_id"], "step": rollup["step"],
                 "latency_ns": rollup["latency_ns"]}
        if attribution:
            attrs["attribution"] = attribution
        self.db.table("event.event").append_rows([{
            "time": now_ns,
            "event_type": etype,
            "resource_type": "step-detector",
            "resource_name": "step_regression",
            "description": desc,
            "attrs": json.dumps(attrs),
        }])
        log.warning("step_regression %s: %s", etype, desc)
        return {"type": etype, "description": desc, **attrs}

    def status(self) -> dict:
        with self._lock:
            return {
                "jobs": {
                    job: {
                        "steps_seen": sc.n,
                        "ewma_ns": int(sc.ewma or 0),
                        "threshold_ns": int(sc.last_threshold_ns)
                        if sc.last_threshold_ns != float("inf") else 0,
                        "firing": bool(self._firing.get(job)),
                    } for job, sc in self._scorers.items()},
                "stats": dict(self.stats),
            }

    # -- loop -----------------------------------------------------------------

    def start(self) -> "StepRegressionDetector":
        self._thread = threading.Thread(
            target=self._run, name="df-step-detector", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.poll()
            except Exception:
                self.stats["errors"] += 1
                log.exception("step detector poll failed")
