"""Leader election for multi-server deployments.

Reference analog: controller/election/election.go:175 (K8s-Lease-backed
single-leader election; the leader runs the controller singletons —
rollups, janitor, command queue — while followers serve ingest+query).

Embedded redesign: an exclusive flock(2) on a lease file. Unlike a
TTL-stamped lease (whose write/verify window can elect two leaders for a
tick), flock gives KERNEL-enforced mutual exclusion: exactly one open file
description holds LOCK_EX at any instant, and a crashed leader's lock
releases the moment its fd closes — no expiry heuristics, no fencing
races. A fencing token still increments under the lock (in the lease file
body) so downstream systems can reject writes from a deposed leader that
hasn't noticed yet. Works wherever flock does (local fs, NFSv4); K8s Lease
objects can layer on via the genesis HTTP client where no shared volume
exists.
"""

from __future__ import annotations

import fcntl
import json
import logging
import os
import socket
import threading
import time

log = logging.getLogger("df.election")


class LeaderElection:
    def __init__(self, lease_path: str, holder: str | None = None,
                 ttl_s: float = 10.0, renew_interval_s: float = 3.0,
                 on_elected=None, on_deposed=None) -> None:
        self.lease_path = lease_path
        if holder is None:
            import uuid
            # instance-unique: two candidates in ONE process (tests,
            # embedded multi-server) must never share an identity
            holder = (f"{socket.gethostname()}-{os.getpid()}-"
                      f"{uuid.uuid4().hex[:8]}")
        self.holder = holder
        self.ttl_s = ttl_s  # kept for API compat; flock needs no TTL
        self.renew_interval_s = renew_interval_s
        self.on_elected = on_elected or (lambda: None)
        self.on_deposed = on_deposed or (lambda: None)
        self.is_leader = False
        self.token = 0          # fencing token of OUR leadership
        self._fd: int | None = None
        self._acquire_lock = threading.Lock()  # ticks + manual calls race
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.stats = {"elections": 0, "renewals": 0, "depositions": 0}

    # -- protocol --------------------------------------------------------------

    def try_acquire(self) -> bool:
        """One acquire attempt; returns current leadership. Holding the
        flock IS leadership — renewal is a no-op heartbeat. Serialized:
        a concurrent losing attempt must never depose a winning one."""
        with self._acquire_lock:
            return self._try_acquire_locked()

    def _try_acquire_locked(self) -> bool:
        if self.is_leader and self._fd is not None:
            self.stats["renewals"] += 1
            return True
        try:
            fd = os.open(self.lease_path, os.O_RDWR | os.O_CREAT, 0o644)
        except OSError as e:
            log.warning("lease open failed: %s", e)
            return self._set_leader(False)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return self._set_leader(False)
        # we own the lock: bump the fencing token and record identity
        try:
            raw = os.pread(fd, 4096, 0)
            prev = json.loads(raw) if raw.strip() else {}
        except (OSError, ValueError):
            prev = {}
        self.token = int(prev.get("token", 0)) + 1
        body = json.dumps({"holder": self.holder, "token": self.token,
                           "acquired_ns": time.time_ns()}).encode()
        try:
            os.ftruncate(fd, 0)
            os.pwrite(fd, body, 0)
            os.fsync(fd)
        except OSError as e:
            log.warning("lease write failed: %s", e)
        self._fd = fd
        return self._set_leader(True)

    def _set_leader(self, leader: bool) -> bool:
        if leader and not self.is_leader:
            self.is_leader = True
            self.stats["elections"] += 1
            log.info("elected leader (%s, token=%d)", self.holder,
                     self.token)
            try:
                self.on_elected()
            except Exception:
                log.exception("on_elected failed")
        elif not leader and self.is_leader:
            self.is_leader = False
            self.stats["depositions"] += 1
            log.warning("leadership lost (%s)", self.holder)
            try:
                self.on_deposed()
            except Exception:
                log.exception("on_deposed failed")
        return self.is_leader

    def resign(self) -> None:
        """Graceful handoff: release the lock so a follower wins at once."""
        with self._acquire_lock:
            self._resign_locked()

    def _resign_locked(self) -> None:
        fd, self._fd = self._fd, None
        if fd is not None:
            try:
                fcntl.flock(fd, fcntl.LOCK_UN)
            except OSError:
                pass
            os.close(fd)
        self._set_leader(False)

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "LeaderElection":
        self.try_acquire()
        self._thread = threading.Thread(
            target=self._run, name="df-election", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)
        self.resign()

    def _run(self) -> None:
        while not self._stop.wait(self.renew_interval_s):
            try:
                self.try_acquire()
            except Exception:
                log.exception("election tick failed")


class K8sLeaseElection:
    """Leader election over coordination.k8s.io/v1 Lease objects — for
    deployments with no shared volume (the flock path needs one). Exactly
    the reference's mechanism (election.go:175): GET the Lease, acquire if
    absent/expired/ours, renew by updating renewTime, with the apiserver's
    optimistic concurrency (resourceVersion) arbitrating races —
    a conflicting update loses with a 409, never yielding two leaders.

    Same callback/flag surface as LeaderElection so Server can use either.
    """

    def __init__(self, name: str, namespace: str = "default",
                 api_base: str | None = None, token: str = "",
                 ca_path: str = "", holder: str | None = None,
                 ttl_s: float = 15.0, renew_interval_s: float = 5.0,
                 on_elected=None, on_deposed=None,
                 insecure_skip_verify: bool = False) -> None:
        from deepflow_tpu.server.genesis import build_api_context, \
            in_cluster_config
        if api_base is None:
            cfg = in_cluster_config()
            if cfg is None:
                raise RuntimeError("not in a cluster and no api_base given")
            api_base, token, ca_path = cfg
        self.api_base = api_base.rstrip("/")
        self._bearer = token        # NEVER in .token: that's the fencing
        # int (shared _set_leader logs it with %d)
        self._ctx = build_api_context(self.api_base, ca_path,
                                      insecure_skip_verify)
        self.name = name
        self.namespace = namespace
        if holder is None:
            import uuid
            holder = (f"{socket.gethostname()}-{os.getpid()}-"
                      f"{uuid.uuid4().hex[:8]}")
        self.holder = holder
        self.ttl_s = ttl_s
        self.renew_interval_s = renew_interval_s
        self.on_elected = on_elected or (lambda: None)
        self.on_deposed = on_deposed or (lambda: None)
        self.is_leader = False
        self.token = 0           # fencing (leaseTransitions), like flock's
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._acquire_lock = threading.Lock()
        # clock-skew-safe expiry (client-go style): time the lease from
        # when WE first observed its renewTime, by our monotonic clock
        self._observed_renew = ("", 0.0)   # (renewTime str, seen_monotonic)
        self._last_ok = 0.0
        self.stats = {"elections": 0, "renewals": 0, "depositions": 0,
                      "conflicts": 0, "errors": 0}

    @property
    def token_fencing(self) -> int:  # back-compat alias
        return self.token

    # -- k8s api ---------------------------------------------------------------

    def _url(self) -> str:
        return (f"{self.api_base}/apis/coordination.k8s.io/v1/namespaces/"
                f"{self.namespace}/leases/{self.name}")

    def _req(self, method: str, body: dict | None = None):
        import urllib.request
        data = json.dumps(body).encode() if body is not None else None
        url = self._url() if method != "POST" else (
            f"{self.api_base}/apis/coordination.k8s.io/v1/namespaces/"
            f"{self.namespace}/leases")
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", "application/json")
        if self._bearer:
            req.add_header("Authorization", f"Bearer {self._bearer}")
        with urllib.request.urlopen(req, timeout=5,
                                    context=self._ctx) as r:
            return json.load(r)

    @staticmethod
    def _now_rfc3339() -> str:
        import datetime
        return datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%S.%f") + "Z"

    # -- protocol --------------------------------------------------------------

    def try_acquire(self) -> bool:
        with self._acquire_lock:
            try:
                out = self._try_acquire_locked()
                self._last_ok = time.monotonic()
                return out
            except Exception as e:
                self.stats["errors"] += 1
                # a transient apiserver blip must not flap the singletons:
                # the lease is still validly OURS until its ttl passes, so
                # keep leading within that grace window (client-go retries
                # inside the renew deadline the same way)
                if self.is_leader and \
                        time.monotonic() - self._last_ok < self.ttl_s:
                    log.warning("k8s lease renew error (still within "
                                "ttl grace): %s", e)
                    return True
                log.warning("k8s lease election error: %s", e)
                return self._set_leader(False)

    def _try_acquire_locked(self) -> bool:
        import urllib.error
        try:
            lease = self._req("GET")
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise
            # no lease yet: CREATE arbitrates the race (409 loses)
            try:
                self._req("POST", self._body(transitions=1))
                self.token = 1
                return self._set_leader(True)
            except urllib.error.HTTPError as ce:
                if ce.code == 409:
                    self.stats["conflicts"] += 1
                    return self._set_leader(False)
                raise
        spec = lease.get("spec", {})
        holder = spec.get("holderIdentity", "")
        renew_str = spec.get("renewTime", "") or ""
        ttl = float(spec.get("leaseDurationSeconds", self.ttl_s))
        # skew-safe: expire ttl after WE first saw this renewTime value
        # (remote clocks may disagree with ours by more than the ttl)
        now_mono = time.monotonic()
        if renew_str != self._observed_renew[0]:
            self._observed_renew = (renew_str, now_mono)
        expired = (now_mono - self._observed_renew[1]) > ttl or \
            not renew_str
        if holder != self.holder and not expired:
            return self._set_leader(False)
        transitions = int(spec.get("leaseTransitions", 0))
        if holder != self.holder:
            transitions += 1
        body = self._body(transitions=transitions)
        body["metadata"]["resourceVersion"] = \
            lease["metadata"].get("resourceVersion", "")
        try:
            self._req("PUT", body)
        except urllib.error.HTTPError as ce:
            if ce.code == 409:  # another candidate won the update race
                self.stats["conflicts"] += 1
                return self._set_leader(False)
            raise
        self.token = transitions
        if holder == self.holder:
            self.stats["renewals"] += 1
        return self._set_leader(True)

    def _body(self, transitions: int) -> dict:
        return {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"name": self.name, "namespace": self.namespace},
            "spec": {
                "holderIdentity": self.holder,
                "leaseDurationSeconds": int(self.ttl_s),
                "renewTime": self._now_rfc3339(),
                "leaseTransitions": transitions,
            },
        }

    _set_leader = LeaderElection._set_leader

    def resign(self) -> None:
        with self._acquire_lock:
            if self.is_leader:
                try:
                    lease = self._req("GET")
                    spec = lease.get("spec", {})
                    if spec.get("holderIdentity") == self.holder:
                        body = self._body(
                            transitions=int(
                                spec.get("leaseTransitions", 0)))
                        del body["spec"]["renewTime"]  # absent renewTime
                        # == expired NOW (the skew-safe observer ignores
                        # timestamp VALUES, but treats a missing one as
                        # immediately expired)
                        body["metadata"]["resourceVersion"] = \
                            lease["metadata"].get("resourceVersion", "")
                        self._req("PUT", body)
                except Exception as e:
                    # failed expiry-PUT delays failover by up to ttl_s:
                    # that must be diagnosable
                    log.warning("lease resign write failed (followers "
                                "wait out the ttl): %s", e)
            self._set_leader(False)

    # -- lifecycle (same shape as LeaderElection) ------------------------------

    start = LeaderElection.start
    stop = LeaderElection.stop
    _run = LeaderElection._run
