"""Leader election for multi-server deployments.

Reference analog: controller/election/election.go:175 (K8s-Lease-backed
single-leader election; the leader runs the controller singletons —
rollups, janitor, command queue — while followers serve ingest+query).

Embedded redesign: an exclusive flock(2) on a lease file. Unlike a
TTL-stamped lease (whose write/verify window can elect two leaders for a
tick), flock gives KERNEL-enforced mutual exclusion: exactly one open file
description holds LOCK_EX at any instant, and a crashed leader's lock
releases the moment its fd closes — no expiry heuristics, no fencing
races. A fencing token still increments under the lock (in the lease file
body) so downstream systems can reject writes from a deposed leader that
hasn't noticed yet. Works wherever flock does (local fs, NFSv4); K8s Lease
objects can layer on via the genesis HTTP client where no shared volume
exists.
"""

from __future__ import annotations

import fcntl
import json
import logging
import os
import socket
import threading
import time

log = logging.getLogger("df.election")


class LeaderElection:
    def __init__(self, lease_path: str, holder: str | None = None,
                 ttl_s: float = 10.0, renew_interval_s: float = 3.0,
                 on_elected=None, on_deposed=None) -> None:
        self.lease_path = lease_path
        if holder is None:
            import uuid
            # instance-unique: two candidates in ONE process (tests,
            # embedded multi-server) must never share an identity
            holder = (f"{socket.gethostname()}-{os.getpid()}-"
                      f"{uuid.uuid4().hex[:8]}")
        self.holder = holder
        self.ttl_s = ttl_s  # kept for API compat; flock needs no TTL
        self.renew_interval_s = renew_interval_s
        self.on_elected = on_elected or (lambda: None)
        self.on_deposed = on_deposed or (lambda: None)
        self.is_leader = False
        self.token = 0          # fencing token of OUR leadership
        self._fd: int | None = None
        self._acquire_lock = threading.Lock()  # ticks + manual calls race
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.stats = {"elections": 0, "renewals": 0, "depositions": 0}

    # -- protocol --------------------------------------------------------------

    def try_acquire(self) -> bool:
        """One acquire attempt; returns current leadership. Holding the
        flock IS leadership — renewal is a no-op heartbeat. Serialized:
        a concurrent losing attempt must never depose a winning one."""
        with self._acquire_lock:
            return self._try_acquire_locked()

    def _try_acquire_locked(self) -> bool:
        if self.is_leader and self._fd is not None:
            self.stats["renewals"] += 1
            return True
        try:
            fd = os.open(self.lease_path, os.O_RDWR | os.O_CREAT, 0o644)
        except OSError as e:
            log.warning("lease open failed: %s", e)
            return self._set_leader(False)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return self._set_leader(False)
        # we own the lock: bump the fencing token and record identity
        try:
            raw = os.pread(fd, 4096, 0)
            prev = json.loads(raw) if raw.strip() else {}
        except (OSError, ValueError):
            prev = {}
        self.token = int(prev.get("token", 0)) + 1
        body = json.dumps({"holder": self.holder, "token": self.token,
                           "acquired_ns": time.time_ns()}).encode()
        try:
            os.ftruncate(fd, 0)
            os.pwrite(fd, body, 0)
            os.fsync(fd)
        except OSError as e:
            log.warning("lease write failed: %s", e)
        self._fd = fd
        return self._set_leader(True)

    def _set_leader(self, leader: bool) -> bool:
        if leader and not self.is_leader:
            self.is_leader = True
            self.stats["elections"] += 1
            log.info("elected leader (%s, token=%d)", self.holder,
                     self.token)
            try:
                self.on_elected()
            except Exception:
                log.exception("on_elected failed")
        elif not leader and self.is_leader:
            self.is_leader = False
            self.stats["depositions"] += 1
            log.warning("leadership lost (%s)", self.holder)
            try:
                self.on_deposed()
            except Exception:
                log.exception("on_deposed failed")
        return self.is_leader

    def resign(self) -> None:
        """Graceful handoff: release the lock so a follower wins at once."""
        with self._acquire_lock:
            self._resign_locked()

    def _resign_locked(self) -> None:
        fd, self._fd = self._fd, None
        if fd is not None:
            try:
                fcntl.flock(fd, fcntl.LOCK_UN)
            except OSError:
                pass
            os.close(fd)
        self._set_leader(False)

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "LeaderElection":
        self.try_acquire()
        self._thread = threading.Thread(
            target=self._run, name="df-election", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)
        self.resign()

    def _run(self) -> None:
        while not self._stop.wait(self.renew_interval_s):
            try:
                self.try_acquire()
            except Exception:
                log.exception("election tick failed")
