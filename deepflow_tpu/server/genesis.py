"""K8s genesis: list-watch the cluster resource model into the platform
tables.

Reference analog: agent/src/platform/kubernetes/api_watcher.rs (pod/node
list-watch) + server/controller/genesis/genesis.go:54 (resource ingestion).
Redesign: the watcher lives server-side (one watcher per cluster, not one
per agent) and feeds the PodIpIndex + ResourceIndex used by the ingest
decoders to tag both sides of every flow by IP (pods, service ClusterIPs,
nodes, subnets). No kubernetes client library — raw HTTP against the
apiserver with the in-cluster service-account token, list + watch with
resourceVersion resume and bounded backoff, one loop per resource kind.
"""

from __future__ import annotations

import json
import logging
import os
import ssl
import threading
import urllib.request

from deepflow_tpu.server.platform_info import (
    NodeInfo, PodInfo, PodIpIndex, ResourceIndex, ServiceInfo)

log = logging.getLogger("df.genesis")

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def in_cluster_config() -> tuple[str, str, str] | None:
    """(api_base, token, ca_path) from the pod environment, or None."""
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    token_path = os.path.join(_SA_DIR, "token")
    if not host or not os.path.exists(token_path):
        return None
    with open(token_path) as f:
        token = f.read().strip()
    ca = os.path.join(_SA_DIR, "ca.crt")
    return (f"https://{host}:{port}", token,
            ca if os.path.exists(ca) else "")


def build_api_context(api_base: str, ca_path: str = "",
                      insecure_skip_verify: bool = False):
    """Shared apiserver TLS context policy (genesis + lease election):
    verified CA, or EXPLICIT opt-out with a loud warning — never silent
    unverified TLS under a bearer token."""
    if not api_base.startswith("https"):
        return None
    if ca_path:
        return ssl.create_default_context(cafile=ca_path)
    if insecure_skip_verify:
        log.warning("k8s api: TLS verification DISABLED "
                    "(insecure_skip_verify)")
        return ssl._create_unverified_context()
    raise ValueError("https api_base needs ca_path "
                     "(or explicit insecure_skip_verify=True)")


class _ResourceLoop:
    """One list+watch loop for one resource kind. `apply(etype, obj,
    emit_events)` returns the reconcile keys the object contributes;
    `reconcile(seen)` evicts keys a relist no longer reports (a relist is
    authoritative, not additive)."""

    def __init__(self, genesis: "K8sGenesis", path: str, count_key: str,
                 apply, reconcile) -> None:
        self.g = genesis
        self.path = path
        self.count_key = count_key
        self.apply = apply
        self.reconcile = reconcile
        self.resource_version = ""
        self._thread: threading.Thread | None = None

    def list_once(self) -> int:
        n = 0
        cont = ""
        seen: set = set()
        while True:
            path = f"{self.path}?limit=500"
            if cont:
                path += f"&continue={cont}"
            with self.g._open(path, timeout=30) as r:
                data = json.load(r)
            for item in data.get("items", []):
                # relist reconciles STATE; it must not re-emit
                # resource-added events for survivors of a watch gap
                keys = self.apply("ADDED", item, emit_events=False)
                if keys:
                    seen.update(keys)
                n += 1
            meta = data.get("metadata", {})
            self.resource_version = meta.get("resourceVersion",
                                             self.resource_version)
            cont = meta.get("continue", "")
            if not cont:
                break
        self.reconcile(seen)
        self.g.stats[self.count_key] = n
        return n

    def watch_once(self) -> None:
        path = (f"{self.path}?watch=1&allowWatchBookmarks=true"
                f"&timeoutSeconds={self.g.watch_timeout_s}")
        if self.resource_version:
            path += f"&resourceVersion={self.resource_version}"
        with self.g._open(path, timeout=self.g.watch_timeout_s + 30) as r:
            for line in r:
                if self.g._stop.is_set():
                    return
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                etype = ev.get("type", "")
                obj = ev.get("object", {})
                rv = obj.get("metadata", {}).get("resourceVersion")
                if rv:
                    self.resource_version = rv
                if etype == "BOOKMARK":
                    continue
                if etype == "ERROR":
                    # expired resourceVersion: force a relist
                    self.resource_version = ""
                    return
                self.apply(etype, obj, True)
                self.g.stats["events"] += 1

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name=f"df-k8s-{self.count_key}", daemon=True)
        self._thread.start()

    def join(self, timeout: float) -> None:
        if self._thread:
            self._thread.join(timeout=timeout)

    def _run(self) -> None:
        backoff = 1.0
        while not self.g._stop.is_set():
            try:
                if not self.resource_version:
                    self.list_once()
                    self.g.stats["relists"] += 1
                self.watch_once()
                backoff = 1.0
            except Exception as e:
                self.g.stats["errors"] += 1
                # first failure (and every 50th) at WARNING: an RBAC/token
                # problem must be operator-visible, not debug-only
                if self.g.stats["errors"] == 1 or \
                        self.g.stats["errors"] % 50 == 0:
                    log.warning("genesis %s watch error (#%d): %s",
                                self.count_key, self.g.stats["errors"], e)
                else:
                    log.debug("genesis %s watch error: %s",
                              self.count_key, e)
                if self.g._stop.wait(backoff):
                    return
                backoff = min(backoff * 2, 30.0)


class K8sGenesis:
    """Pod (+ Service/Endpoints/Node when a ResourceIndex is attached)
    list-watch -> platform tables."""

    def __init__(self, pod_index: PodIpIndex, api_base: str | None = None,
                 token: str = "", ca_path: str = "",
                 watch_timeout_s: int = 300,
                 insecure_skip_verify: bool = False,
                 event_sink=None,
                 resources: ResourceIndex | None = None) -> None:
        # event_sink(rows) receives resource-change events (reference:
        # controller/recorder resource diffs -> event tables)
        self.event_sink = event_sink
        if api_base is None:
            cfg = in_cluster_config()
            if cfg is None:
                raise RuntimeError("not in a cluster and no api_base given")
            api_base, token, ca_path = cfg
        self.api_base = api_base.rstrip("/")
        self.token = token
        self.watch_timeout_s = watch_timeout_s
        self.pod_index = pod_index
        self.resources = resources
        self._ctx = build_api_context(self.api_base, ca_path,
                                      insecure_skip_verify)
        self._stop = threading.Event()
        self.stats = {"pods": 0, "events": 0, "relists": 0, "errors": 0,
                      "services": 0, "endpoints": 0, "nodes": 0}
        self._loops = [_ResourceLoop(
            self, "/api/v1/pods", "pods", self._apply,
            self.pod_index.retain_ips)]
        if resources is not None:
            self._loops += [
                _ResourceLoop(self, "/api/v1/services", "services",
                              self._apply_service, resources.retain_services),
                _ResourceLoop(self, "/api/v1/endpoints", "endpoints",
                              self._apply_endpoints,
                              resources.retain_endpoints),
                _ResourceLoop(self, "/api/v1/nodes", "nodes",
                              self._apply_node, resources.retain_nodes),
            ]

    # back-compat: tests poke gen.resource_version to force relists
    @property
    def resource_version(self) -> str:
        return self._loops[0].resource_version

    @resource_version.setter
    def resource_version(self, v: str) -> None:
        self._loops[0].resource_version = v

    # -- http -----------------------------------------------------------------

    def _open(self, path: str, timeout: float):
        req = urllib.request.Request(self.api_base + path)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        return urllib.request.urlopen(req, timeout=timeout,
                                      context=self._ctx)

    # -- resource events -------------------------------------------------------

    def _emit_event(self, etype: str, resource_type: str, name: str,
                    description: str) -> None:
        if self.event_sink is None or etype not in ("ADDED", "DELETED"):
            return
        import time as _t
        try:
            self.event_sink([{
                "time": _t.time_ns(),
                "event_type": f"{resource_type}-{etype.lower()}",
                "resource_type": resource_type,
                "resource_name": name,
                "description": description,
            }])
        except Exception:
            log.debug("event sink failed", exc_info=True)

    # -- pods ------------------------------------------------------------------

    @staticmethod
    def _workload_of(pod: dict) -> str:
        for ref in pod.get("metadata", {}).get("ownerReferences", []):
            name = ref.get("name", "")
            if ref.get("kind") == "ReplicaSet":
                # strip the replicaset hash -> deployment name
                return name.rsplit("-", 1)[0] if "-" in name else name
            if ref.get("kind") in ("StatefulSet", "DaemonSet", "Job"):
                return name
        return ""

    def _apply(self, event_type: str, pod: dict,
               emit_events: bool = True) -> set:
        meta = pod.get("metadata", {})
        status = pod.get("status", {})
        ips = [e.get("ip") for e in status.get("podIPs", [])
               if e.get("ip")]
        if not ips and status.get("podIP"):
            ips = [status["podIP"]]
        info = PodInfo(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", ""),
            node=pod.get("spec", {}).get("nodeName", ""),
            workload=self._workload_of(pod),
            labels=meta.get("labels", {}) or {},
        )
        if event_type == "DELETED":
            for ip in ips:
                self.pod_index.remove_ip(ip)
        else:  # ADDED | MODIFIED
            for ip in ips:
                self.pod_index.upsert(ip, info)
        if emit_events:
            self._emit_event(
                event_type, "pod", f"{info.namespace}/{info.name}",
                f"node={info.node} workload={info.workload} "
                f"ips={','.join(ips)}")
        return set(ips)

    # -- services / endpoints / nodes -----------------------------------------

    def _apply_service(self, event_type: str, obj: dict,
                       emit_events: bool = True) -> set:
        meta = obj.get("metadata", {})
        spec = obj.get("spec", {})
        ns, name = meta.get("namespace", ""), meta.get("name", "")
        # defensive: ignore non-Service shapes (shared fake servers)
        if not name or ("clusterIP" not in spec and "ports" not in spec):
            return set()
        if event_type == "DELETED":
            self.resources.remove_service(ns, name)
        else:
            self.resources.upsert_service(ServiceInfo(
                name=name, namespace=ns,
                cluster_ip=spec.get("clusterIP", "") or "",
                svc_type=spec.get("type", "ClusterIP"),
                ports=tuple(p.get("port") for p in spec.get("ports", [])
                            if p.get("port"))))
        if emit_events:
            self._emit_event(event_type, "service", f"{ns}/{name}",
                             f"cluster_ip={spec.get('clusterIP', '')}")
        return {(ns, name)}

    def _apply_endpoints(self, event_type: str, obj: dict,
                         emit_events: bool = True) -> set:
        meta = obj.get("metadata", {})
        ns, name = meta.get("namespace", ""), meta.get("name", "")
        # K8s serializes subsets with omitempty: a service scaled to zero
        # arrives WITHOUT the key and must clear its stale pod-ip mappings.
        # Only objects that are clearly another kind (pods have spec/status;
        # Endpoints never do) are skipped.
        if not name or ("subsets" not in obj
                        and ("spec" in obj or "status" in obj)):
            return set()
        if event_type == "DELETED":
            self.resources.set_endpoints(ns, name, ())
            return set()
        ips = [a.get("ip")
               for s in (obj.get("subsets") or [])
               for a in (s.get("addresses") or [])
               if a.get("ip")]
        self.resources.set_endpoints(ns, name, ips)
        return {(ns, name)}

    def _apply_node(self, event_type: str, obj: dict,
                    emit_events: bool = True) -> set:
        meta = obj.get("metadata", {})
        name = meta.get("name", "")
        status = obj.get("status", {})
        if not name or "addresses" not in status:
            return set()
        if event_type == "DELETED":
            self.resources.remove_node(name)
            self._emit_event(event_type, "node", name, "")
            return set()
        labels = meta.get("labels", {}) or {}
        spec = obj.get("spec", {})
        internal = ""
        for a in status.get("addresses") or []:
            if a.get("type") == "InternalIP":
                internal = a.get("address", "")
                break
        cidrs = spec.get("podCIDRs") or \
            ([spec["podCIDR"]] if spec.get("podCIDR") else [])
        node = NodeInfo(
            name=name,
            az=labels.get("topology.kubernetes.io/zone", ""),
            region=labels.get("topology.kubernetes.io/region", ""),
            internal_ip=internal, pod_cidrs=tuple(cidrs))
        self.resources.upsert_node(node)
        if emit_events:
            self._emit_event(event_type, "node", name,
                             f"az={node.az} ip={internal}")
        return {name}

    # -- back-compat single-loop entry points (tests drive these) -------------

    def list_once(self) -> int:
        return self._loops[0].list_once()

    def watch_once(self) -> None:
        self._loops[0].watch_once()

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "K8sGenesis":
        for loop in self._loops:
            loop.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        for loop in self._loops:
            loop.join(timeout=3.0)
