"""K8s genesis: list-watch the cluster resource model into the platform
tables.

Reference analog: agent/src/platform/kubernetes/api_watcher.rs (pod/node
list-watch) + server/controller/genesis/genesis.go:54 (resource ingestion).
Redesign: the watcher lives server-side (one watcher per cluster, not one
per agent) and feeds the PodIpIndex used by the ingest decoders to tag both
sides of every flow by IP. No kubernetes client library — raw HTTP against
the apiserver with the in-cluster service-account token, list + watch with
resourceVersion resume and bounded backoff.
"""

from __future__ import annotations

import json
import logging
import os
import ssl
import threading
import urllib.request

from deepflow_tpu.server.platform_info import PodInfo, PodIpIndex

log = logging.getLogger("df.genesis")

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def in_cluster_config() -> tuple[str, str, str] | None:
    """(api_base, token, ca_path) from the pod environment, or None."""
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    token_path = os.path.join(_SA_DIR, "token")
    if not host or not os.path.exists(token_path):
        return None
    with open(token_path) as f:
        token = f.read().strip()
    ca = os.path.join(_SA_DIR, "ca.crt")
    return (f"https://{host}:{port}", token,
            ca if os.path.exists(ca) else "")


def build_api_context(api_base: str, ca_path: str = "",
                      insecure_skip_verify: bool = False):
    """Shared apiserver TLS context policy (genesis + lease election):
    verified CA, or EXPLICIT opt-out with a loud warning — never silent
    unverified TLS under a bearer token."""
    if not api_base.startswith("https"):
        return None
    if ca_path:
        return ssl.create_default_context(cafile=ca_path)
    if insecure_skip_verify:
        log.warning("k8s api: TLS verification DISABLED "
                    "(insecure_skip_verify)")
        return ssl._create_unverified_context()
    raise ValueError("https api_base needs ca_path "
                     "(or explicit insecure_skip_verify=True)")


class K8sGenesis:
    """Pod list-watch -> PodIpIndex."""

    def __init__(self, pod_index: PodIpIndex, api_base: str | None = None,
                 token: str = "", ca_path: str = "",
                 watch_timeout_s: int = 300,
                 insecure_skip_verify: bool = False,
                 event_sink=None) -> None:
        # event_sink(rows) receives resource-change events (reference:
        # controller/recorder resource diffs -> event tables)
        self.event_sink = event_sink
        if api_base is None:
            cfg = in_cluster_config()
            if cfg is None:
                raise RuntimeError("not in a cluster and no api_base given")
            api_base, token, ca_path = cfg
        self.api_base = api_base.rstrip("/")
        self.token = token
        self.watch_timeout_s = watch_timeout_s
        self.pod_index = pod_index
        self._ctx = build_api_context(self.api_base, ca_path,
                                      insecure_skip_verify)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.resource_version = ""
        self.stats = {"pods": 0, "events": 0, "relists": 0, "errors": 0}

    # -- http -----------------------------------------------------------------

    def _open(self, path: str, timeout: float):
        req = urllib.request.Request(self.api_base + path)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        return urllib.request.urlopen(req, timeout=timeout,
                                      context=self._ctx)

    # -- resource handling -----------------------------------------------------

    @staticmethod
    def _workload_of(pod: dict) -> str:
        for ref in pod.get("metadata", {}).get("ownerReferences", []):
            name = ref.get("name", "")
            if ref.get("kind") == "ReplicaSet":
                # strip the replicaset hash -> deployment name
                return name.rsplit("-", 1)[0] if "-" in name else name
            if ref.get("kind") in ("StatefulSet", "DaemonSet", "Job"):
                return name
        return ""

    def _apply(self, event_type: str, pod: dict,
               emit_events: bool = True) -> None:
        meta = pod.get("metadata", {})
        status = pod.get("status", {})
        ips = [e.get("ip") for e in status.get("podIPs", [])
               if e.get("ip")]
        if not ips and status.get("podIP"):
            ips = [status["podIP"]]
        info = PodInfo(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", ""),
            node=pod.get("spec", {}).get("nodeName", ""),
            workload=self._workload_of(pod),
            labels=meta.get("labels", {}) or {},
        )
        if event_type == "DELETED":
            for ip in ips:
                self.pod_index.remove_ip(ip)
        else:  # ADDED | MODIFIED
            for ip in ips:
                self.pod_index.upsert(ip, info)
        if emit_events and self.event_sink is not None and \
                event_type in ("ADDED", "DELETED"):
            import time as _t
            try:
                self.event_sink([{
                    "time": _t.time_ns(),
                    "event_type": f"pod-{event_type.lower()}",
                    "resource_type": "pod",
                    "resource_name": f"{info.namespace}/{info.name}",
                    "description": f"node={info.node} "
                                   f"workload={info.workload} "
                                   f"ips={','.join(ips)}",
                }])
            except Exception:
                log.debug("event sink failed", exc_info=True)

    # -- list + watch ----------------------------------------------------------

    def list_once(self) -> int:
        """Full pod list; returns pod count. Sets the watch resume point
        and RECONCILES: IPs whose pods vanished during a watch gap are
        evicted (a relist is authoritative, not additive)."""
        n = 0
        cont = ""
        seen_ips: set[str] = set()
        while True:
            path = "/api/v1/pods?limit=500"
            if cont:
                path += f"&continue={cont}"
            with self._open(path, timeout=30) as r:
                data = json.load(r)
            for pod in data.get("items", []):
                # relist reconciles STATE; it must not re-emit pod-added
                # for pods that merely survived a watch gap
                self._apply("ADDED", pod, emit_events=False)
                status = pod.get("status", {})
                for e in status.get("podIPs", []):
                    if e.get("ip"):
                        seen_ips.add(e["ip"])
                if status.get("podIP"):
                    seen_ips.add(status["podIP"])
                n += 1
            meta = data.get("metadata", {})
            self.resource_version = meta.get("resourceVersion",
                                             self.resource_version)
            cont = meta.get("continue", "")
            if not cont:
                break
        self.pod_index.retain_ips(seen_ips)
        self.stats["pods"] = n
        return n

    def watch_once(self) -> None:
        """One watch connection; applies events until it ends."""
        path = (f"/api/v1/pods?watch=1&allowWatchBookmarks=true"
                f"&timeoutSeconds={self.watch_timeout_s}")
        if self.resource_version:
            path += f"&resourceVersion={self.resource_version}"
        with self._open(path, timeout=self.watch_timeout_s + 30) as r:
            for line in r:
                if self._stop.is_set():
                    return
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                etype = ev.get("type", "")
                obj = ev.get("object", {})
                rv = obj.get("metadata", {}).get("resourceVersion")
                if rv:
                    self.resource_version = rv
                if etype == "BOOKMARK":
                    continue
                if etype == "ERROR":
                    # expired resourceVersion: force a relist
                    self.resource_version = ""
                    return
                self._apply(etype, obj)
                self.stats["events"] += 1

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "K8sGenesis":
        self._thread = threading.Thread(
            target=self._run, name="df-k8s-genesis", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=3.0)

    def _run(self) -> None:
        backoff = 1.0
        while not self._stop.is_set():
            try:
                if not self.resource_version:
                    self.list_once()
                    self.stats["relists"] += 1
                self.watch_once()
                backoff = 1.0
            except Exception as e:
                self.stats["errors"] += 1
                # first failure (and every 50th) at WARNING: an RBAC/token
                # problem must be operator-visible, not debug-only
                if self.stats["errors"] == 1 or \
                        self.stats["errors"] % 50 == 0:
                    log.warning("genesis watch error (#%d): %s",
                                self.stats["errors"], e)
                else:
                    log.debug("genesis watch error: %s", e)
                if self._stop.wait(backoff):
                    return
                backoff = min(backoff * 2, 30.0)
