"""K8s genesis: list-watch the cluster resource model into the platform
tables.

Reference analog: agent/src/platform/kubernetes/api_watcher.rs (pod/node
list-watch) + server/controller/genesis/genesis.go:54 (resource ingestion).
Redesign: the watcher lives server-side (one watcher per cluster, not one
per agent) and feeds the PodIpIndex + ResourceIndex used by the ingest
decoders to tag both sides of every flow by IP (pods, service ClusterIPs,
nodes, subnets). No kubernetes client library — raw HTTP against the
apiserver with the in-cluster service-account token, list + watch with
resourceVersion resume and bounded backoff, one loop per resource kind.
"""

from __future__ import annotations

import json
import logging
import os
import ssl
import threading
import urllib.request

from deepflow_tpu.server.platform_info import (
    NodeInfo, PodInfo, PodIpIndex, ResourceIndex, ServiceInfo)

log = logging.getLogger("df.genesis")

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def in_cluster_config() -> tuple[str, str, str] | None:
    """(api_base, token, ca_path) from the pod environment, or None."""
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    token_path = os.path.join(_SA_DIR, "token")
    if not host or not os.path.exists(token_path):
        return None
    with open(token_path) as f:
        token = f.read().strip()
    ca = os.path.join(_SA_DIR, "ca.crt")
    return (f"https://{host}:{port}", token,
            ca if os.path.exists(ca) else "")


def build_api_context(api_base: str, ca_path: str = "",
                      insecure_skip_verify: bool = False):
    """Shared apiserver TLS context policy (genesis + lease election):
    verified CA, or EXPLICIT opt-out with a loud warning — never silent
    unverified TLS under a bearer token."""
    if not api_base.startswith("https"):
        return None
    if ca_path:
        return ssl.create_default_context(cafile=ca_path)
    if insecure_skip_verify:
        log.warning("k8s api: TLS verification DISABLED "
                    "(insecure_skip_verify)")
        return ssl._create_unverified_context()
    raise ValueError("https api_base needs ca_path "
                     "(or explicit insecure_skip_verify=True)")


class _ResourceLoop:
    """One list+watch loop for one resource kind. `apply(etype, obj,
    emit_events)` returns the reconcile keys the object contributes;
    `reconcile(seen)` evicts keys a relist no longer reports (a relist is
    authoritative, not additive)."""

    def __init__(self, genesis: "K8sGenesis", path: str, count_key: str,
                 apply, reconcile) -> None:
        self.g = genesis
        self.path = path
        self.count_key = count_key
        self.apply = apply
        self.reconcile = reconcile
        self.resource_version = ""
        self._thread: threading.Thread | None = None

    def list_once(self) -> int:
        n = 0
        cont = ""
        seen: set = set()
        while True:
            path = f"{self.path}?limit=500"
            if cont:
                path += f"&continue={cont}"
            with self.g._open(path, timeout=30) as r:
                data = json.load(r)
            for item in data.get("items", []):
                # relist reconciles STATE; it must not re-emit
                # resource-added events for survivors of a watch gap
                keys = self.apply("ADDED", item, emit_events=False)
                if keys:
                    seen.update(keys)
                n += 1
            meta = data.get("metadata", {})
            self.resource_version = meta.get("resourceVersion",
                                             self.resource_version)
            cont = meta.get("continue", "")
            if not cont:
                break
        self.reconcile(seen)
        self.g.stats[self.count_key] = n
        return n

    def watch_once(self) -> None:
        path = (f"{self.path}?watch=1&allowWatchBookmarks=true"
                f"&timeoutSeconds={self.g.watch_timeout_s}")
        if self.resource_version:
            path += f"&resourceVersion={self.resource_version}"
        with self.g._open(path, timeout=self.g.watch_timeout_s + 30) as r:
            for line in r:
                if self.g._stop.is_set():
                    return
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                etype = ev.get("type", "")
                obj = ev.get("object", {})
                rv = obj.get("metadata", {}).get("resourceVersion")
                if rv:
                    self.resource_version = rv
                if etype == "BOOKMARK":
                    continue
                if etype == "ERROR":
                    # expired resourceVersion: force a relist
                    self.resource_version = ""
                    return
                self.apply(etype, obj, True)
                self.g.stats["events"] += 1

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name=f"df-k8s-{self.count_key}", daemon=True)
        self._thread.start()

    def join(self, timeout: float) -> None:
        if self._thread:
            self._thread.join(timeout=timeout)

    def _run(self) -> None:
        backoff = 1.0
        # a watch legitimately blocks up to watch_timeout_s between
        # beats — declare it so the deadman widens this stage's window
        hb = self.g.telemetry.heartbeat(
            f"genesis.{self.count_key}",
            interval_hint_s=float(self.g.watch_timeout_s))
        while not self.g._stop.is_set():
            hb.beat(progress=self.g.stats["events"])
            try:
                if not self.resource_version:
                    self.list_once()
                    self.g.stats["relists"] += 1
                self.watch_once()
                backoff = 1.0
            except Exception as e:
                self.g.stats["errors"] += 1
                # first failure (and every 50th) at WARNING: an RBAC/token
                # problem must be operator-visible, not debug-only
                if self.g.stats["errors"] == 1 or \
                        self.g.stats["errors"] % 50 == 0:
                    log.warning("genesis %s watch error (#%d): %s",
                                self.count_key, self.g.stats["errors"], e)
                else:
                    log.debug("genesis %s watch error: %s",
                              self.count_key, e)
                if self.g._stop.wait(backoff):
                    return
                backoff = min(backoff * 2, 30.0)


class K8sGenesis:
    """Pod (+ Service/Endpoints/Node when a ResourceIndex is attached)
    list-watch -> platform tables."""

    def __init__(self, pod_index: PodIpIndex, api_base: str | None = None,
                 token: str = "", ca_path: str = "",
                 watch_timeout_s: int = 300,
                 insecure_skip_verify: bool = False,
                 event_sink=None,
                 resources: ResourceIndex | None = None,
                 telemetry=None) -> None:
        # event_sink(rows) receives resource-change events through the
        # snapshot-diff recorder (reference: controller/recorder resource
        # diffs -> event tables): added/deleted AND attribute-level
        # modified events with before/after payloads
        from deepflow_tpu.server.recorder import ResourceRecorder
        self.event_sink = event_sink
        self.recorder = ResourceRecorder(event_sink)
        self._workload_pods: dict[str, set] = {}
        if api_base is None:
            cfg = in_cluster_config()
            if cfg is None:
                raise RuntimeError("not in a cluster and no api_base given")
            api_base, token, ca_path = cfg
        self.api_base = api_base.rstrip("/")
        self.token = token
        self.watch_timeout_s = watch_timeout_s
        self.pod_index = pod_index
        self.resources = resources
        self._ctx = build_api_context(self.api_base, ca_path,
                                      insecure_skip_verify)
        self._stop = threading.Event()
        if telemetry is None:
            from deepflow_tpu.telemetry import Telemetry
            telemetry = Telemetry("server", enabled=False)
        self.telemetry = telemetry
        self.stats = {"pods": 0, "events": 0, "relists": 0, "errors": 0,
                      "services": 0, "endpoints": 0, "nodes": 0}
        self._loops = [_ResourceLoop(
            self, "/api/v1/pods", "pods", self._apply,
            self._retain_pods)]
        if resources is not None:
            self._loops += [
                _ResourceLoop(self, "/api/v1/services", "services",
                              self._apply_service, self._retain_services),
                _ResourceLoop(self, "/api/v1/endpoints", "endpoints",
                              self._apply_endpoints,
                              resources.retain_endpoints),
                _ResourceLoop(self, "/api/v1/nodes", "nodes",
                              self._apply_node, self._retain_nodes),
            ]

    # -- relist reconciliation (state AND recorder) ---------------------------

    def _retain_pods(self, seen: set) -> None:
        # split the mixed reconcile set _apply returns: plain strings are
        # pod IPs, ("__pod__", key) tuples are live pod identities
        # (IP-less Pending pods appear ONLY as the latter)
        ips = {k for k in seen if isinstance(k, str)}
        live = {k[1] for k in seen if isinstance(k, tuple)}
        self.pod_index.retain_ips(ips)
        # objects that vanished during a watch gap get their deleted
        # events here — the relist is authoritative
        self.recorder.reconcile("pod", live)
        live_w: dict[str, set] = {}
        for wkey, members in self._workload_pods.items():
            kept = members & live
            if kept:
                live_w[wkey] = kept
        self._workload_pods = live_w
        self.recorder.reconcile("workload", set(live_w))

    def _retain_services(self, keys: set) -> None:
        self.resources.retain_services(keys)
        self.recorder.reconcile("service",
                                {f"{ns}/{n}" for ns, n in keys})

    def _retain_nodes(self, names: set) -> None:
        self.resources.retain_nodes(names)
        self.recorder.reconcile("node", set(names))

    # back-compat: tests poke gen.resource_version to force relists
    @property
    def resource_version(self) -> str:
        return self._loops[0].resource_version

    @resource_version.setter
    def resource_version(self, v: str) -> None:
        self._loops[0].resource_version = v

    # -- http -----------------------------------------------------------------

    def _open(self, path: str, timeout: float):
        req = urllib.request.Request(self.api_base + path)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        return urllib.request.urlopen(req, timeout=timeout,
                                      context=self._ctx)

    # -- pods ------------------------------------------------------------------

    @staticmethod
    def _workload_of(pod: dict) -> str:
        for ref in pod.get("metadata", {}).get("ownerReferences", []):
            name = ref.get("name", "")
            if ref.get("kind") == "ReplicaSet":
                # strip the replicaset hash -> deployment name
                return name.rsplit("-", 1)[0] if "-" in name else name
            if ref.get("kind") in ("StatefulSet", "DaemonSet", "Job"):
                return name
        return ""

    def _apply(self, event_type: str, pod: dict,
               emit_events: bool = True) -> set:
        meta = pod.get("metadata", {})
        status = pod.get("status", {})
        ips = [e.get("ip") for e in status.get("podIPs", [])
               if e.get("ip")]
        if not ips and status.get("podIP"):
            ips = [status["podIP"]]
        info = PodInfo(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", ""),
            node=pod.get("spec", {}).get("nodeName", ""),
            workload=self._workload_of(pod),
            labels=meta.get("labels", {}) or {},
        )
        key = f"{info.namespace}/{info.name}"
        deleted = event_type == "DELETED"
        if deleted:
            for ip in ips:
                self.pod_index.remove_ip(ip)
        else:  # ADDED | MODIFIED
            for ip in ips:
                self.pod_index.upsert(ip, info)
        self.recorder.observe(
            "pod", key,
            None if deleted else {"node": info.node,
                                  "workload": info.workload,
                                  "ips": sorted(ips)},
            deleted=deleted, emit=emit_events)
        # derived workload lifecycle (reference records pod_group state):
        # first pod of a workload -> workload-added; last gone -> deleted
        if info.workload:
            wkey = f"{info.namespace}/{info.workload}"
            members = self._workload_pods.setdefault(wkey, set())
            if deleted:
                members.discard(key)
                if not members:
                    self._workload_pods.pop(wkey, None)
                    self.recorder.observe("workload", wkey, None,
                                          deleted=True, emit=emit_events)
            else:
                members.add(key)
                self.recorder.observe(
                    "workload", wkey, {"namespace": info.namespace},
                    emit=emit_events)
        # reconcile keys: the pod's IPs (pod_index retention) plus a
        # name marker — a Pending pod has NO ip yet but is still alive,
        # and the recorder's relist reconcile must not declare it dead
        return set(ips) | {("__pod__", key)}

    # -- services / endpoints / nodes -----------------------------------------

    def _apply_service(self, event_type: str, obj: dict,
                       emit_events: bool = True) -> set:
        meta = obj.get("metadata", {})
        spec = obj.get("spec", {})
        ns, name = meta.get("namespace", ""), meta.get("name", "")
        # defensive: ignore non-Service shapes (shared fake servers)
        if not name or ("clusterIP" not in spec and "ports" not in spec):
            return set()
        deleted = event_type == "DELETED"
        ports = tuple(p.get("port") for p in spec.get("ports", [])
                      if p.get("port"))
        if deleted:
            self.resources.remove_service(ns, name)
        else:
            self.resources.upsert_service(ServiceInfo(
                name=name, namespace=ns,
                cluster_ip=spec.get("clusterIP", "") or "",
                svc_type=spec.get("type", "ClusterIP"),
                ports=ports))
        self.recorder.observe(
            "service", f"{ns}/{name}",
            None if deleted else {
                "cluster_ip": spec.get("clusterIP", "") or "",
                "type": spec.get("type", "ClusterIP"),
                "ports": sorted(ports)},
            deleted=deleted, emit=emit_events)
        return {(ns, name)}

    def _apply_endpoints(self, event_type: str, obj: dict,
                         emit_events: bool = True) -> set:
        meta = obj.get("metadata", {})
        ns, name = meta.get("namespace", ""), meta.get("name", "")
        # K8s serializes subsets with omitempty: a service scaled to zero
        # arrives WITHOUT the key and must clear its stale pod-ip mappings.
        # Only objects that are clearly another kind (pods have spec/status;
        # Endpoints never do) are skipped.
        if not name or ("subsets" not in obj
                        and ("spec" in obj or "status" in obj)):
            return set()
        if event_type == "DELETED":
            self.resources.set_endpoints(ns, name, ())
            return set()
        ips = [a.get("ip")
               for s in (obj.get("subsets") or [])
               for a in (s.get("addresses") or [])
               if a.get("ip")]
        self.resources.set_endpoints(ns, name, ips)
        return {(ns, name)}

    def _apply_node(self, event_type: str, obj: dict,
                    emit_events: bool = True) -> set:
        meta = obj.get("metadata", {})
        name = meta.get("name", "")
        status = obj.get("status", {})
        if not name or "addresses" not in status:
            return set()
        if event_type == "DELETED":
            self.resources.remove_node(name)
            self.recorder.observe("node", name, None, deleted=True,
                                  emit=emit_events)
            return set()
        labels = meta.get("labels", {}) or {}
        spec = obj.get("spec", {})
        internal = ""
        for a in status.get("addresses") or []:
            if a.get("type") == "InternalIP":
                internal = a.get("address", "")
                break
        cidrs = spec.get("podCIDRs") or \
            ([spec["podCIDR"]] if spec.get("podCIDR") else [])
        node = NodeInfo(
            name=name,
            az=labels.get("topology.kubernetes.io/zone", ""),
            region=labels.get("topology.kubernetes.io/region", ""),
            internal_ip=internal, pod_cidrs=tuple(cidrs))
        self.resources.upsert_node(node)
        # node readiness is the attr ops ask about first ("did the node
        # go NotReady right before the regression?")
        ready = ""
        for cond in status.get("conditions") or []:
            if cond.get("type") == "Ready":
                ready = cond.get("status", "")
                break
        self.recorder.observe(
            "node", name,
            {"az": node.az, "region": node.region,
             "internal_ip": internal, "pod_cidrs": sorted(cidrs),
             "ready": ready},
            emit=emit_events)
        return {name}

    # -- back-compat single-loop entry points (tests drive these) -------------

    def list_once(self) -> int:
        return self._loops[0].list_once()

    def watch_once(self) -> None:
        self._loops[0].watch_once()

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "K8sGenesis":
        for loop in self._loops:
            loop.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        for loop in self._loops:
            loop.join(timeout=3.0)
