"""Background tier flusher + the durability gate for ack release.

This is what makes "ack after durable write" (PR 5's transport contract)
actually mean DURABLE when persistent storage is on. Decoders stop
observing seqs into the receiver's SeqAckTracker directly; instead they
park them in the DurabilityGate after decode+write. Each flush cycle:

  1. drain the gate (every parked seq's rows are in stripes/chunks by
     now — the decoder parked it only after its table writes returned)
  2. fold the drained seqs into a private floor tracker -> candidate
     per-agent contiguous floors
  3. db.flush_to_tier(ack_floors=floors): ONE atomic manifest commit
     persists the rows AND the floors (store/tiered.py ordering)
  4. only then observe the seqs into the receiver's tracker — the acks
     that now go out describe state that survives SIGKILL

A crash between any two steps is safe: rows committed but seqs not yet
released -> the floors in the manifest already cover them, so the restart
seeds dedup above the retransmit; rows not committed -> seqs never
released, agent retransmits, rows are written again (the lost copy was
RAM-only). Exactly-once either way.

Without a gate (storage off) decoders keep the old direct-observe path —
zero behavior change for in-memory servers.
"""

from __future__ import annotations

import logging
import os
import threading
import time

from deepflow_tpu.server.receiver import SeqAckTracker

log = logging.getLogger("df.flusher")


class DurabilityGate:
    """Seqs written to RAM tables but not yet durable on disk."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._pending: list[tuple[int, int]] = []  # (agent_id, seq)

    def add(self, agent_id: int, seq: int) -> None:
        with self._lock:
            self._pending.append((agent_id, seq))

    def drain(self) -> list[tuple[int, int]]:
        with self._lock:
            out, self._pending = self._pending, []
            return out

    def requeue(self, items: list[tuple[int, int]]) -> None:
        """A flush commit failed (disk full, ...): the seqs stay gated —
        releasing them would ack rows that are not durable."""
        with self._lock:
            self._pending = items + self._pending

    def __len__(self) -> int:
        with self._lock:
            return len(self._pending)


class Flusher:
    """Periodic tier flush; owns the durable-ack release ordering."""

    def __init__(self, db, gate: DurabilityGate | None = None,
                 seq_tracker=None, interval_s: float = 1.0,
                 telemetry=None) -> None:
        self.db = db
        self.gate = gate
        self.seq_tracker = seq_tracker  # the receiver's (release target)
        self.interval_s = interval_s
        # private floor bookkeeping: same contiguity algebra as the
        # receiver's tracker, but advanced BEFORE the commit so the
        # manifest can carry the floors the release will create
        self._floors = SeqAckTracker()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._flush_lock = threading.Lock()  # run loop vs final flush
        # spare-core policy: zlib in the flusher thread only pays when a
        # core is free to run it — on a single-core host the deflate
        # serializes straight against the ingest hot path
        self.compress = (os.cpu_count() or 1) > 1
        self.stats = {"flushes": 0, "rows_flushed": 0, "seqs_released": 0,
                      "errors": 0, "flush_ns": 0}
        # consecutive failed commits (disk full, I/O error): drives the
        # run loop's bounded exponential backoff AND the write-pressure
        # signal Server._flusher_backlog feeds the PressureController —
        # sustained write failure sheds load at the agents instead of
        # letting the gate grow without bound. Reset on the first
        # successful flush.
        self.consec_errors = 0
        if telemetry is None:
            from deepflow_tpu.telemetry import Telemetry
            telemetry = Telemetry("server", enabled=False)
        self._telemetry = telemetry

    def seed_floors(self, floors: dict[int, int]) -> None:
        for agent_id, contig in floors.items():
            self._floors.seed(agent_id, contig)

    def flush_once(self, seal: bool | None = None) -> int:
        """One gate-drain + commit + release cycle (also the final drain
        on stop). Returns rows committed.

        ``seal`` controls whether open stripe buffers are force-sealed
        into the commit. Default (None) is group-commit: seal only when
        drained acks are actually waiting on durability — idle cycles
        then flush naturally-sealed chunks without chopping the ingest
        hot path's open buffers into per-interval slivers. stop() and
        explicit callers force True."""
        with self._flush_lock:
            pend = self.gate.drain() if self.gate is not None else []
            t0 = time.perf_counter_ns()
            floors = None
            if pend:
                for agent_id, seq in pend:
                    self._floors.observe(agent_id, seq)
                floors = self._floors.snapshot()
            if seal is None:
                seal = bool(pend)
            try:
                rows = self.db.flush_to_tier(ack_floors=floors, seal=seal,
                                             compress=self.compress)
            except Exception:
                self.stats["errors"] += 1
                self.consec_errors += 1
                if pend and self.gate is not None:
                    self.gate.requeue(pend)
                raise
            self.consec_errors = 0
            # release: the acks now describe durable state
            if self.seq_tracker is not None:
                for agent_id, seq in pend:
                    self.seq_tracker.observe(agent_id, seq)
            self.stats["flushes"] += 1
            self.stats["rows_flushed"] += rows
            self.stats["seqs_released"] += len(pend)
            self.stats["flush_ns"] += time.perf_counter_ns() - t0
            return rows

    def start(self) -> "Flusher":
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="df-flusher", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Final flush AFTER the decoders drained: everything they wrote
        (and parked) becomes durable and acked before the server exits."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        try:
            self.flush_once(seal=True)
        except Exception:
            log.exception("final tier flush failed")

    def _run(self) -> None:
        hb = self._telemetry.heartbeat(
            "flusher", interval_hint_s=max(1.0, self.interval_s))
        hb.beat()
        while True:
            # bounded exponential backoff after failed commits: a full
            # disk gets probed at 1x, 2x, 4x ... up to 30s, not hammered
            # every interval; gate entries stay parked (acks withheld)
            # so the transport spool absorbs the stall
            wait = self.interval_s
            if self.consec_errors:
                wait = min(self.interval_s * (2 ** min(
                    self.consec_errors, 6)), 30.0)
            if self._stop.wait(wait):
                return
            hb.beat(progress=self.stats["flushes"])
            try:
                self.flush_once()
            except Exception:
                log.exception("tier flush failed (attempt %d)",
                              self.consec_errors)


class Compactor:
    """Periodic tier compaction: merges the flusher's small sealed
    segments into time-sorted format-v2 runs (store/tiered.py compact).
    Runs well below the flush cadence — each cycle is one crash-safe
    manifest commit per merge group, and any v1 segments it meets are
    migrated to v2 as a side effect (online migrate-on-compact), so a
    long-lived server converges to all-v2 with zero downtime."""

    def __init__(self, db, interval_s: float = 60.0,
                 telemetry=None) -> None:
        self.db = db
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.stats = {"cycles": 0, "runs_built": 0,
                      "segments_replaced": 0, "rows": 0,
                      "segments_migrated": 0, "errors": 0,
                      "compact_ns": 0}
        if telemetry is None:
            from deepflow_tpu.telemetry import Telemetry
            telemetry = Telemetry("server", enabled=False)
        self._telemetry = telemetry

    def compact_once(self) -> dict:
        """One full-database compaction pass (also the dfctl/test entry
        point). Builds on the shared query pool when one is configured."""
        from deepflow_tpu.query.pool import get_pool
        t0 = time.perf_counter_ns()
        res = self.db.compact_tier(pool=get_pool())
        self.stats["cycles"] += 1
        for k in ("runs_built", "segments_replaced", "rows",
                  "segments_migrated"):
            self.stats[k] += res.get(k, 0)
        self.stats["compact_ns"] += time.perf_counter_ns() - t0
        return res

    def start(self) -> "Compactor":
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="df-compactor", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        hb = self._telemetry.heartbeat(
            "compactor", interval_hint_s=max(1.0, self.interval_s))
        hb.beat()
        while not self._stop.wait(self.interval_s):
            hb.beat(progress=self.stats["cycles"])
            try:
                self.compact_once()
            except Exception:
                self.stats["errors"] += 1
                log.exception("tier compaction failed")
