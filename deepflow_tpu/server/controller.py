"""Controller: agent management over gRPC (trisolaris-lite).

Reference analog: server/controller/trisolaris (sync_push.go:166 AgentEvent.
Sync — per-agent SyncResponse with versioned config + platform data) and
trisolaris/services/grpc/agentsynchronize/process_info.go (GPID allocation).
gRPC service methods are hand-registered (generic handlers) because the
image has protoc but not grpcio-tools.

Fleet scale: the server runs on grpc.aio — every Push stream is a coroutine
awaiting a per-group condition, not a pinned thread, so thousands of agents
hold push streams concurrently (the reference's pushmanager serves its
fleet the same way; round 1's thread-pool design capped at 48).
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time

import grpc

from deepflow_tpu.proto import pb
from deepflow_tpu.server.platform_info import AgentInfo, PlatformInfoTable

log = logging.getLogger("df.controller")

DEFAULT_AGENT_CONFIG_YAML = b"""\
# deepflow-tpu rendered agent config (controller-pushed)
profiler:
  enabled: true
  sample_hz: 99.0
  emit_interval_s: 1.0
tpuprobe:
  enabled: true
  source: auto
  trace_interval_s: 10.0
  trace_duration_ms: 1000
stats_interval_s: 10.0
"""


class AgentRegistry:
    """Agent identity + state; the vtap cache analog."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._by_key: dict[tuple, dict] = {}
        self._next_id = 1

    def register(self, ctrl_ip: str, hostname: str, agent_id: int,
                 request: "pb.SyncRequest | None" = None) -> dict:
        key = (ctrl_ip, hostname)
        with self._lock:
            entry = self._by_key.get(key)
            if entry is None:
                entry = {
                    "agent_id": agent_id or self._next_id,
                    "ctrl_ip": ctrl_ip,
                    "hostname": hostname,
                    "first_seen_ns": time.time_ns(),
                    "syncs": 0,
                }
                if not agent_id:
                    self._next_id += 1
                else:
                    self._next_id = max(self._next_id, agent_id + 1)
                self._by_key[key] = entry
            entry["last_seen_ns"] = time.time_ns()
            entry["syncs"] = entry.get("syncs", 0) + 1
            if request is not None:
                # health view for /v1/agents (reference: vtap list,
                # cli/ctl/agent.go:49 — the primary fleet ops surface)
                entry["state"] = int(request.state)
                entry["exception_bitmap"] = int(request.exception_bitmap)
                entry["degraded"] = bool(request.exception_bitmap)
                entry["version"] = request.version
                entry["cpu_usage"] = round(float(request.cpu_usage), 2)
                entry["mem_bytes"] = int(request.mem_bytes)
                entry["agent_group"] = request.agent_group or "default"
                entry["config_version"] = int(request.config_version)
                if request.HasField("clock_offset_ns"):
                    entry["clock_offset_ms"] = round(
                        request.clock_offset_ns / 1e6, 3)
                else:
                    # unmeasured, not "0 ms skew" — operators must be able
                    # to tell the two apart in /v1/agents
                    entry["clock_offset_ms"] = None
            return entry

    def list(self) -> list[dict]:
        with self._lock:
            return [dict(v) for v in self._by_key.values()]

    def group_of(self, agent_id: int) -> str:
        with self._lock:
            for e in self._by_key.values():
                if e["agent_id"] == agent_id:
                    return e.get("agent_group", "default")
        return "default"


class GpidAllocator:
    """Global process IDs: (agent_id, pid) -> gpid, plus the 5-tuple table
    that lets the ingester join client/server sides of one connection
    (reference §2.8 GPID glue).

    Lifecycle: each agent's sync is a full snapshot — entries that agent
    reported before and no longer does are dropped (a dead process's
    ephemeral port must not attribute a later process's flows), and a TTL
    sweep retires entries from agents that stopped syncing entirely.

    Entries are bucketed PER AGENT: a sync diffs only that agent's bucket
    against the flat lookup index instead of rebuilding the whole
    fleet-wide table (which made every sync O(fleet) — at 1k agents x 30s
    sync the controller spent most of its lock hold time re-dict-ing
    other agents' unchanged entries). The TTL sweep likewise moved off
    the per-sync path onto an interval: it only has work to do when an
    agent has been silent for minutes, so running it per sync was pure
    overhead."""

    ENTRY_TTL_S = 600.0
    SWEEP_INTERVAL_S = 60.0

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._gpids: dict[tuple, int] = {}
        # agent_id -> {(ip, port, proto, role): entry} (that agent's
        # last full snapshot) and its last-sync monotonic timestamp
        self._by_agent: dict[int, dict[tuple, pb.GpidEntry]] = {}
        self._agent_ts: dict[int, float] = {}
        # flat (ip, port, proto, role) -> entry index for ingest-side
        # point reads; maintained incrementally from the buckets
        self._flat: dict[tuple, pb.GpidEntry] = {}
        self._last_sweep = 0.0
        self._next = 1

    def gpid_for(self, agent_id: int, pid: int) -> int:
        key = (agent_id, pid)
        with self._lock:
            g = self._gpids.get(key)
            if g is None:
                g = self._next
                self._next += 1
                self._gpids[key] = g
            return g

    def sync(self, req: pb.GpidSyncRequest) -> pb.GpidSyncResponse:
        now = time.monotonic()
        with self._lock:
            bucket: dict[tuple, pb.GpidEntry] = {}
            for e in req.entries:
                e.agent_id = req.agent_id  # never trust the entry field
                e.gpid = self._gpids.get((req.agent_id, e.pid), 0) or \
                    self._alloc_locked(req.agent_id, e.pid)
                bucket[(bytes(e.ip), e.port, int(e.proto), e.role)] = e
            # snapshot semantics: this agent's stale entries go away now —
            # only keys this agent owned and stopped reporting are touched
            old = self._by_agent.get(req.agent_id)
            if old:
                for k in old:
                    if k not in bucket:
                        cur = self._flat.get(k)
                        if cur is not None and \
                                cur.agent_id == req.agent_id:
                            del self._flat[k]
            self._by_agent[req.agent_id] = bucket
            self._agent_ts[req.agent_id] = now
            self._flat.update(bucket)
            # TTL sweep (agents that stopped syncing: crash, drain) runs
            # on an interval, not per sync
            if now - self._last_sweep >= self.SWEEP_INTERVAL_S:
                self._sweep_locked(now)
            # echo only the REQUESTER's entries (gpids now filled) — the
            # ingest-side join lives here, and echoing the whole fleet's
            # socket table back on every scan would be O(fleet) waste
            resp = pb.GpidSyncResponse()
            resp.entries.extend(req.entries)
            return resp

    def _sweep_locked(self, now: float) -> None:
        self._last_sweep = now
        cutoff = now - self.ENTRY_TTL_S
        for aid in [a for a, ts in self._agent_ts.items() if ts < cutoff]:
            for k in self._by_agent.pop(aid, {}):
                cur = self._flat.get(k)
                if cur is not None and cur.agent_id == aid:
                    del self._flat[k]
            del self._agent_ts[aid]

    def _alloc_locked(self, agent_id: int, pid: int) -> int:
        g = self._next
        self._next += 1
        self._gpids[(agent_id, pid)] = g
        return g

    def lookup(self, ip: bytes, port: int, proto: int) -> int:
        """Ingest-side join (reference grpc_platformdata.go:2047): map a
        flow endpoint to its global process id; tries server role (exact
        listen tuple) then client role."""
        e = self._entry_for(ip, port, proto)
        return e.gpid if e is not None else 0

    def name_lookup(self, ip: bytes, port: int, proto: int
                    ) -> tuple[int, str]:
        """(gpid, process_name) for a flow endpoint — lets flow logs show
        identity for processes that never loaded the preload interposer
        (socket-inode scan supplies the entries)."""
        e = self._entry_for(ip, port, proto)
        return (e.gpid, e.process_name) if e is not None else (0, "")

    def _entry_for(self, ip: bytes, port: int, proto: int):
        # exact-match ONLY: wildcard binds are expanded into concrete
        # local addresses agent-side (socket_scan.scan_entries) — a
        # server-side any-ip fallback would attribute flows toward
        # REMOTE endpoints on the same port to a local listener
        flat = self._flat  # GIL-atomic point reads; entry objects are
        # never mutated after insertion (a sync inserts fresh ones)
        for role in (1, 0):
            e = flat.get((ip, port, proto, role))
            if e is not None:
                return e
        return None


class PackageRepo:
    """Versioned agent packages for OTA rollout (reference: the repo
    that `deepflow-ctl repo agent upload` feeds, served to agents over
    the Upgrade stream — here a unary fetch; packages are MB-scale
    tarballs of the python package tree)."""

    MAX_PACKAGE = 64 << 20
    MAX_VERSIONS = 8   # keep the repo bounded; oldest evicted

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # name -> ordered {version: (data, sha256)}
        self._pkgs: dict[str, dict[str, tuple[bytes, str]]] = {}

    def upload(self, name: str, version: str, data: bytes) -> dict:
        import hashlib
        if not version:
            raise ValueError("version required")
        if len(data) > self.MAX_PACKAGE:
            raise ValueError(f"package over {self.MAX_PACKAGE} bytes")
        sha = hashlib.sha256(data).hexdigest()
        with self._lock:
            versions = self._pkgs.setdefault(name, {})
            versions[version] = (data, sha)
            while len(versions) > self.MAX_VERSIONS:
                versions.pop(next(iter(versions)))
        return {"name": name, "version": version, "sha256": sha,
                "size": len(data)}

    def get(self, name: str, version: str = ""
            ) -> tuple[str, bytes, str] | None:
        with self._lock:
            versions = self._pkgs.get(name)
            if not versions:
                return None
            if not version:
                version = next(reversed(versions))  # latest upload
            entry = versions.get(version)
            if entry is None:
                return None
            return version, entry[0], entry[1]

    def list(self) -> dict:
        with self._lock:
            return {name: [{"version": v, "sha256": d[1],
                            "size": len(d[0])}
                           for v, d in versions.items()]
                    for name, versions in self._pkgs.items()}


class ConfigStore:
    """Versioned agent-group configs (reference: agent-group config YAML
    validated against the template; push on version bump)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._configs: dict[str, tuple[bytes, int]] = {
            "default": (DEFAULT_AGENT_CONFIG_YAML, 1)}
        self._listeners: list = []  # callables(group, yaml, version)
        # boot nonce: version counters reset with the process; agents use
        # the epoch to tell "restarted controller" from "stale response"
        self.epoch = time.time_ns() & 0xFFFFFFFFFFFF

    def subscribe(self, fn) -> None:
        with self._lock:
            self._listeners.append(fn)

    def get(self, group: str = "default") -> tuple[bytes, int]:
        with self._lock:
            return self._configs.get(group, self._configs["default"])

    def update(self, group: str, yaml_bytes: bytes) -> int:
        self.validate(yaml_bytes)
        with self._lock:
            _, version = self._configs.get(group, (b"", 0))
            version += 1
            self._configs[group] = (yaml_bytes, version)
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(group, yaml_bytes, version)
            except Exception:
                log.exception("config listener failed")
        return version

    @staticmethod
    def validate(yaml_bytes: bytes) -> None:
        import yaml
        from deepflow_tpu.agent.config import AgentConfig
        data = yaml.safe_load(yaml_bytes) or {}
        if not isinstance(data, dict):
            raise ValueError("agent config must be a YAML mapping")
        AgentConfig.from_dict(data).validate()


class CommandQueue:
    """Per-agent remote-exec queue + result store (agent.proto:18 analog:
    controller queues registry commands, agents pick them up on sync)."""

    MAX_RESULTS = 1024       # oldest evicted; dfctl polls promptly
    MAX_PENDING_PER_AGENT = 64
    INFLIGHT_TTL_S = 30.0    # redeliver if no result (at-least-once)

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._pending: dict[int, list] = {}    # agent_id -> [RemoteCommand]
        self._inflight: dict[int, tuple] = {}  # cmd_id -> (agent, rc, ts)
        self._results: dict[int, dict] = {}    # cmd_id -> result dict
        self._next_id = 1

    def submit(self, agent_id: int, cmd: str, args: list[str]) -> int:
        with self._lock:
            q = self._pending.setdefault(agent_id, [])
            if len(q) >= self.MAX_PENDING_PER_AGENT:
                raise ValueError(
                    f"agent {agent_id} has {len(q)} undelivered commands "
                    "(is it syncing?)")
            cid = self._next_id
            self._next_id += 1
            rc = pb.RemoteCommand(id=cid, cmd=cmd)
            rc.args.extend(args)
            q.append(rc)
            self._results[cid] = {"id": cid, "agent_id": agent_id,
                                  "cmd": cmd, "state": "pending"}
            while len(self._results) > self.MAX_RESULTS:
                self._results.pop(next(iter(self._results)))
            return cid

    def take_pending(self, agent_id: int) -> list:
        """Delivery is AT-LEAST-ONCE: commands stay in-flight until a
        result arrives; a lost Sync response redelivers after a TTL."""
        now = time.monotonic()
        with self._lock:
            out = self._pending.pop(agent_id, [])
            for cid, (aid, rc, ts) in list(self._inflight.items()):
                if aid == agent_id and now - ts > self.INFLIGHT_TTL_S:
                    out.append(rc)
                    del self._inflight[cid]
            for rc in out:
                self._inflight[rc.id] = (agent_id, rc, now)
            return out

    def deliver_results(self, results) -> None:
        with self._lock:
            for r in results:
                self._inflight.pop(r.id, None)
                entry = self._results.get(r.id)
                if entry is not None:
                    entry.update(state="done", exit_code=r.exit_code,
                                 output=r.output)

    def result(self, cmd_id: int) -> dict | None:
        with self._lock:
            r = self._results.get(cmd_id)
            return dict(r) if r else None


class Controller:
    """The gRPC Synchronizer service + shared state."""

    def __init__(self, platform_table: PlatformInfoTable,
                 host: str = "127.0.0.1", port: int = 20035,
                 pod_index=None, ring_provider=None, qos=None) -> None:
        self.platform_table = platform_table
        # closed-loop backpressure (deepflow_tpu/qos): each Sync response
        # carries this agent's org pressure directive so the fleet
        # degrades gracefully instead of overrunning the ingest tier
        self.qos = qos
        self.pod_index = pod_index  # K8s genesis resource model (server's)
        # zero-arg callable -> HashRing | None: when a replication ring
        # is active its per-agent owner order (primary first) wins over
        # the flat analyzer list below
        self.ring_provider = ring_provider
        self.registry = AgentRegistry()
        self.gpids = GpidAllocator()
        # agent-group -> org assignment (reference: controller/db org/team
        # model; redesigned as group-level scoping — the group is already
        # the config-routing identity, so it is the tenancy boundary too).
        # Unassigned groups belong to the default org 1.
        self._orgs: dict[str, int] = {}
        self._orgs_lock = threading.Lock()
        from deepflow_tpu.server.prom_encoder import PromEncoder
        self.prom_encoder = PromEncoder()
        self.commands = CommandQueue()
        # analyzer (ingest node) list for agent rebalance; never-set =
        # agents keep their configured servers; set-then-cleared = agents
        # REVERT to them
        self._analyzers: list[str] = []
        self._analyzers_managed = False
        self._analyzer_lock = threading.Lock()
        self.configs = ConfigStore()
        self.packages = PackageRepo()
        self.host = host
        self.port = port
        self._aio_server = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._loop_thread: threading.Thread | None = None
        self._loop_ready = threading.Event()
        self._stop_evt: asyncio.Event | None = None
        # cluster-wide platform snapshot (genesis -> recorder analog)
        self._platform_lock = threading.Lock()
        self._platforms: dict[int, pb.PlatformData] = {}
        self._platform_version = 1
        # push wake: per-group asyncio.Condition, owned by the loop thread;
        # streams track their own last-sent version (newest-wins, no queues)
        self._push_conds: dict[str, asyncio.Condition] = {}
        self.push_streams = 0
        self.configs.subscribe(self._on_config_update)

    # -- rpc handlers ---------------------------------------------------------

    def Sync(self, request: pb.SyncRequest, context) -> pb.SyncResponse:
        entry = self.registry.register(
            request.ctrl_ip, request.hostname, request.agent_id,
            request=request)
        agent_id = entry["agent_id"]
        resp = pb.SyncResponse()
        resp.status = pb.SUCCESS
        resp.agent_id = agent_id

        cfg, version = self.configs.get(request.agent_group or "default")
        # resend on version mismatch OR epoch mismatch: after a restart the
        # new store's version can coincide with the agent's stale one while
        # the content differs
        if request.config_version != version or \
                request.config_epoch != self.configs.epoch:
            resp.user_config_yaml = cfg
        resp.config_version = version
        resp.config_epoch = self.configs.epoch

        if request.HasField("platform"):
            self._ingest_platform(agent_id, request.platform)
        if request.HasField("clock_offset_ns"):
            # ingest-time normalization: decoders shift this agent's
            # absolute timestamps onto the controller clock
            self.platform_table.set_clock_offset(agent_id,
                                                 request.clock_offset_ns)
        for proc in request.processes:
            self.gpids.gpid_for(agent_id, proc.pid)

        with self._platform_lock:
            # version only: agents pull the snapshot when they grow a
            # policy/labeler consumer for it (reference pushes full
            # platform data because its agents label packets with it)
            resp.platform_version = self._platform_version
        if request.command_results:
            self.commands.deliver_results(request.command_results)
        for rc in self.commands.take_pending(agent_id):
            resp.commands.append(rc)
        addrs = self.assign_analyzers(agent_id)
        with self._analyzer_lock:
            resp.analyzer_assignment = (self._analyzers_managed
                                        or bool(addrs))
        for addr in addrs:
            resp.analyzer_addrs.append(addr)
        qos = self.qos
        if qos is not None and qos.enabled:
            org = self.org_of_group(request.agent_group or "default")
            d = qos.directive(org)
            if d is not None:
                resp.qos.pressure_level = int(d["pressure_level"])
                resp.qos.sample_rate = float(d["sample_rate"])
                resp.qos.weight = int(d["weight"])
                resp.qos.rate_fps = float(d["rate_fps"])
                resp.qos.updated_ns = int(d["updated_ns"])
        return resp

    def Ntp(self, request: pb.NtpRequest, context) -> pb.NtpResponse:
        """4-timestamp NTP exchange (reference: agent/src/rpc/ntp.rs).
        t2 is stamped on entry, t3 right before serialization."""
        resp = pb.NtpResponse()
        resp.t1_ns = request.t1_ns
        resp.t2_ns = time.time_ns()
        resp.t3_ns = time.time_ns()
        return resp

    def set_analyzers(self, addrs: list[str]) -> None:
        from deepflow_tpu.agent.config import _parse_addr
        for a in addrs:  # reject bad addresses HERE, not per-agent later
            _parse_addr(a)  # raises ValueError
        with self._analyzer_lock:
            self._analyzers = list(dict.fromkeys(addrs))
            self._analyzers_managed = True

    def analyzers(self) -> list[str]:
        with self._analyzer_lock:
            return list(self._analyzers)

    def assign_analyzers(self, agent_id: int) -> list[str]:
        """Per-agent ingest destinations. With a replication ring
        active, the ring's owner order (primary first, then replicas)
        IS the assignment — the synchronizer pushes it down
        analyzer_addrs and the agent's ReplicatedSender adopts it on
        the next sync, completing a leader-driven rebalance. Otherwise:
        rendezvous hashing over the flat analyzer list — even spread,
        minimal churn when the node set changes (reference:
        controller/monitor analyzer rebalance)."""
        import hashlib
        ring = self.ring_provider() if self.ring_provider else None
        if ring is not None:
            addrs = ring.ingest_addrs(agent_id)
            if addrs:
                return addrs
        with self._analyzer_lock:
            addrs = list(self._analyzers)
        if not addrs:
            return []
        def weight(addr: str) -> int:
            h = hashlib.blake2s(f"{agent_id}|{addr}".encode(),
                                digest_size=8)
            return int.from_bytes(h.digest(), "big")
        return sorted(addrs, key=weight, reverse=True)

    def GpidSync(self, request: pb.GpidSyncRequest,
                 context) -> pb.GpidSyncResponse:
        return self.gpids.sync(request)

    def PodMap(self, request: pb.PodMapRequest,
               context) -> pb.PodMapResponse:
        """Cluster resource model -> agents (labeler feed). Entries only
        when the agent's version is stale (steady-state syncs are tiny)."""
        resp = pb.PodMapResponse()
        if self.pod_index is None:
            return resp
        resp.version = self.pod_index.version
        resp.epoch = self.configs.epoch  # restart-coincidence guard
        if request.version == resp.version and \
                request.epoch == resp.epoch:
            return resp
        for ip, pod in self.pod_index.items_copy():
            e = resp.entries.add()
            e.cidr = f"{ip}/32" if ":" not in ip else f"{ip}/128"
            e.pod = pod.name
            e.namespace = pod.namespace
            e.workload = pod.workload
            e.node = pod.node
        return resp

    def _push_cond(self, group: str) -> asyncio.Condition:
        """Loop-thread only."""
        cond = self._push_conds.get(group)
        if cond is None:
            cond = self._push_conds[group] = asyncio.Condition()
        return cond

    async def Push(self, request: pb.SyncRequest, context):
        """Server-streaming: config-change notifications (reference:
        trisolaris push on version bump, sync_push.go pushmanager).

        Coroutine per stream, not thread per stream: each stream compares
        its last-sent version against the store and awaits a shared
        per-group condition — no stream cap, no per-stream queues to
        overflow, newest-wins by construction."""
        group = request.agent_group or "default"
        sent_version = int(request.config_version)
        sent_epoch = int(request.config_epoch)
        cond = self._push_cond(group)
        self.push_streams += 1
        try:
            while True:
                cfg, version = self.configs.get(group)
                if version != sent_version or \
                        sent_epoch != self.configs.epoch:
                    resp = pb.SyncResponse()
                    resp.status = pb.SUCCESS
                    resp.user_config_yaml = cfg
                    resp.config_version = version
                    resp.config_epoch = self.configs.epoch
                    yield resp
                    sent_version = version
                    sent_epoch = self.configs.epoch
                async with cond:
                    try:
                        await asyncio.wait_for(cond.wait(), timeout=5.0)
                    except asyncio.TimeoutError:
                        pass  # periodic re-check also covers missed wakes
        finally:
            self.push_streams -= 1

    def _on_config_update(self, group: str, yaml_bytes: bytes,
                          version: int) -> None:
        """Called from arbitrary threads (HTTP API); wake the loop."""
        loop = self._loop
        if loop is None or not loop.is_running():
            return

        def _notify() -> None:
            cond = self._push_cond(group)

            async def _do() -> None:
                async with cond:
                    cond.notify_all()

            asyncio.ensure_future(_do())

        loop.call_soon_threadsafe(_notify)

    def assign_org(self, group: str, org_id: int) -> None:
        """Assign an agent group to an org (takes effect on the agents'
        next platform sync). org 1 assignments just clear the entry."""
        with self._orgs_lock:
            if int(org_id) == 1:
                self._orgs.pop(group, None)
            else:
                self._orgs[group] = int(org_id)

    def org_of_group(self, group: str) -> int:
        with self._orgs_lock:
            return self._orgs.get(group, 1)

    def org_assignments(self) -> dict:
        with self._orgs_lock:
            return dict(self._orgs)

    def _ingest_platform(self, agent_id: int, p: pb.PlatformData) -> None:
        """Genesis upload -> platform snapshot + ingester tag table."""
        with self._platform_lock:
            prev = self._platforms.get(agent_id)
            if prev is None or prev.SerializeToString() != \
                    p.SerializeToString():
                self._platforms[agent_id] = pb.PlatformData()
                self._platforms[agent_id].CopyFrom(p)
                self._platform_version += 1
        self.platform_table.update(AgentInfo(
            agent_id=agent_id,
            host=p.hostname,
            pod_name=p.pod_name,
            pod_ns=p.pod_namespace,
            tpu_pod=p.tpu_pod_name,
            tpu_worker=int(p.tpu_worker_id or 0),
            slice_id=p.devices[0].slice_id if p.devices else 0,
            org_id=self.org_of_group(self.registry.group_of(agent_id)),
        ))

    def _merged_platform_locked(self) -> pb.PlatformData:
        merged = pb.PlatformData()
        for p in self._platforms.values():
            merged.devices.extend(p.devices)
            merged.slice_count = max(merged.slice_count, p.slice_count)
        return merged

    # -- server lifecycle -----------------------------------------------------

    def running(self) -> bool:
        return self._loop_thread is not None and \
            self._loop_thread.is_alive()

    def start(self) -> "Controller":
        self._loop_thread = threading.Thread(
            target=self._run_loop, name="df-controller-aio", daemon=True)
        self._loop_thread.start()
        if not self._loop_ready.wait(timeout=10):
            raise RuntimeError("controller event loop failed to start")
        log.info("controller sync up on :%d (aio)", self.port)
        return self

    def _run_loop(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        try:
            loop.run_until_complete(self._serve())
        finally:
            loop.close()

    async def _serve(self) -> None:
        async def sync_h(request, context):
            return self.Sync(request, context)

        async def gpid_h(request, context):
            return self.GpidSync(request, context)

        async def prom_h(request, context):
            return self.prom_encoder.handle(request)

        async def podmap_h(request, context):
            return self.PodMap(request, context)

        async def ntp_h(request, context):
            return self.Ntp(request, context)

        async def pkg_h(request, context):
            got = self.packages.get(request.name, request.version)
            resp = pb.PackageResponse()
            if got is not None:
                resp.version, resp.data, resp.sha256 = got
                resp.found = True
            return resp

        handlers = {
            "Sync": grpc.unary_unary_rpc_method_handler(
                sync_h,
                request_deserializer=pb.SyncRequest.FromString,
                response_serializer=pb.SyncResponse.SerializeToString),
            "GpidSync": grpc.unary_unary_rpc_method_handler(
                gpid_h,
                request_deserializer=pb.GpidSyncRequest.FromString,
                response_serializer=pb.GpidSyncResponse.SerializeToString),
            "PromEncode": grpc.unary_unary_rpc_method_handler(
                prom_h,
                request_deserializer=pb.PromEncodeRequest.FromString,
                response_serializer=pb.PromEncodeResponse.SerializeToString),
            "PodMap": grpc.unary_unary_rpc_method_handler(
                podmap_h,
                request_deserializer=pb.PodMapRequest.FromString,
                response_serializer=pb.PodMapResponse.SerializeToString),
            "Ntp": grpc.unary_unary_rpc_method_handler(
                ntp_h,
                request_deserializer=pb.NtpRequest.FromString,
                response_serializer=pb.NtpResponse.SerializeToString),
            "FetchPackage": grpc.unary_unary_rpc_method_handler(
                pkg_h,
                request_deserializer=pb.PackageRequest.FromString,
                response_serializer=pb.PackageResponse.SerializeToString),
            "Push": grpc.unary_stream_rpc_method_handler(
                self.Push,
                request_deserializer=pb.SyncRequest.FromString,
                response_serializer=pb.SyncResponse.SerializeToString),
        }
        generic = grpc.method_handlers_generic_handler(
            "deepflow_tpu.Synchronizer", handlers)
        server = grpc.aio.server(options=[
            ("grpc.max_receive_message_length", 80 << 20),
            ("grpc.max_send_message_length", 80 << 20)])
        server.add_generic_rpc_handlers((generic,))
        self.port = server.add_insecure_port(f"{self.host}:{self.port}")
        await server.start()
        self._aio_server = server
        self._loop = asyncio.get_running_loop()
        self._stop_evt = asyncio.Event()
        self._loop_ready.set()
        await self._stop_evt.wait()
        await server.stop(grace=0.5)

    def stop(self) -> None:
        loop = self._loop
        if loop is not None and loop.is_running() and \
                self._stop_evt is not None:
            loop.call_soon_threadsafe(self._stop_evt.set)
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=5.0)
            self._loop_thread = None
        self._aio_server = None
        self._loop = None
        self._push_conds.clear()  # Conditions are bound to the dead loop
        self._loop_ready.clear()
