"""Controller: agent management over gRPC (trisolaris-lite).

Reference analog: server/controller/trisolaris (sync_push.go:166 AgentEvent.
Sync — per-agent SyncResponse with versioned config + platform data) and
trisolaris/services/grpc/agentsynchronize/process_info.go (GPID allocation).
gRPC service methods are hand-registered (generic handlers) because the
image has protoc but not grpcio-tools.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from concurrent import futures

import grpc

from deepflow_tpu.proto import pb
from deepflow_tpu.server.platform_info import AgentInfo, PlatformInfoTable

log = logging.getLogger("df.controller")

DEFAULT_AGENT_CONFIG_YAML = b"""\
# deepflow-tpu rendered agent config (controller-pushed)
profiler:
  enabled: true
  sample_hz: 99.0
  emit_interval_s: 1.0
tpuprobe:
  enabled: true
  source: auto
  trace_interval_s: 10.0
  trace_duration_ms: 1000
stats_interval_s: 10.0
"""


class AgentRegistry:
    """Agent identity + state; the vtap cache analog."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._by_key: dict[tuple, dict] = {}
        self._next_id = 1

    def register(self, ctrl_ip: str, hostname: str, agent_id: int) -> dict:
        key = (ctrl_ip, hostname)
        with self._lock:
            entry = self._by_key.get(key)
            if entry is None:
                entry = {
                    "agent_id": agent_id or self._next_id,
                    "ctrl_ip": ctrl_ip,
                    "hostname": hostname,
                    "first_seen_ns": time.time_ns(),
                }
                if not agent_id:
                    self._next_id += 1
                else:
                    self._next_id = max(self._next_id, agent_id + 1)
                self._by_key[key] = entry
            entry["last_seen_ns"] = time.time_ns()
            return entry

    def list(self) -> list[dict]:
        with self._lock:
            return [dict(v) for v in self._by_key.values()]


class GpidAllocator:
    """Global process IDs: (agent_id, pid) -> gpid, plus the 5-tuple table
    that lets the ingester join client/server sides of one connection
    (reference §2.8 GPID glue)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._gpids: dict[tuple, int] = {}
        self._entries: dict[tuple, pb.GpidEntry] = {}
        self._next = 1

    def gpid_for(self, agent_id: int, pid: int) -> int:
        key = (agent_id, pid)
        with self._lock:
            g = self._gpids.get(key)
            if g is None:
                g = self._next
                self._next += 1
                self._gpids[key] = g
            return g

    def sync(self, req: pb.GpidSyncRequest) -> pb.GpidSyncResponse:
        with self._lock:
            for e in req.entries:
                e.gpid = self._gpids.get((req.agent_id, e.pid), 0) or \
                    self._alloc_locked(req.agent_id, e.pid)
                self._entries[(bytes(e.ip), e.port, int(e.proto),
                               e.role)] = e
            resp = pb.GpidSyncResponse()
            resp.entries.extend(self._entries.values())
            return resp

    def _alloc_locked(self, agent_id: int, pid: int) -> int:
        g = self._next
        self._next += 1
        self._gpids[(agent_id, pid)] = g
        return g


class ConfigStore:
    """Versioned agent-group configs (reference: agent-group config YAML
    validated against the template; push on version bump)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._configs: dict[str, tuple[bytes, int]] = {
            "default": (DEFAULT_AGENT_CONFIG_YAML, 1)}
        self._listeners: list = []  # callables(group, yaml, version)
        # boot nonce: version counters reset with the process; agents use
        # the epoch to tell "restarted controller" from "stale response"
        self.epoch = time.time_ns() & 0xFFFFFFFFFFFF

    def subscribe(self, fn) -> None:
        with self._lock:
            self._listeners.append(fn)

    def get(self, group: str = "default") -> tuple[bytes, int]:
        with self._lock:
            return self._configs.get(group, self._configs["default"])

    def update(self, group: str, yaml_bytes: bytes) -> int:
        self.validate(yaml_bytes)
        with self._lock:
            _, version = self._configs.get(group, (b"", 0))
            version += 1
            self._configs[group] = (yaml_bytes, version)
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(group, yaml_bytes, version)
            except Exception:
                log.exception("config listener failed")
        return version

    @staticmethod
    def validate(yaml_bytes: bytes) -> None:
        import yaml
        from deepflow_tpu.agent.config import AgentConfig
        data = yaml.safe_load(yaml_bytes) or {}
        if not isinstance(data, dict):
            raise ValueError("agent config must be a YAML mapping")
        AgentConfig.from_dict(data).validate()


class Controller:
    """The gRPC Synchronizer service + shared state."""

    def __init__(self, platform_table: PlatformInfoTable,
                 host: str = "127.0.0.1", port: int = 20035) -> None:
        self.platform_table = platform_table
        self.registry = AgentRegistry()
        self.gpids = GpidAllocator()
        self.configs = ConfigStore()
        self.host = host
        self.port = port
        self._server: grpc.Server | None = None
        # cluster-wide platform snapshot (genesis -> recorder analog)
        self._platform_lock = threading.Lock()
        self._platforms: dict[int, pb.PlatformData] = {}
        self._platform_version = 1
        # push subscribers: (group, queue) per connected agent stream
        self._push_lock = threading.Lock()
        self._push_subs: list[tuple[str, "queue.Queue"]] = []
        self.configs.subscribe(self._on_config_update)

    # -- rpc handlers ---------------------------------------------------------

    def Sync(self, request: pb.SyncRequest, context) -> pb.SyncResponse:
        entry = self.registry.register(
            request.ctrl_ip, request.hostname, request.agent_id)
        agent_id = entry["agent_id"]
        resp = pb.SyncResponse()
        resp.status = pb.SUCCESS
        resp.agent_id = agent_id

        cfg, version = self.configs.get(request.agent_group or "default")
        # resend on version mismatch OR epoch mismatch: after a restart the
        # new store's version can coincide with the agent's stale one while
        # the content differs
        if request.config_version != version or \
                request.config_epoch != self.configs.epoch:
            resp.user_config_yaml = cfg
        resp.config_version = version
        resp.config_epoch = self.configs.epoch

        if request.HasField("platform"):
            self._ingest_platform(agent_id, request.platform)
        for proc in request.processes:
            self.gpids.gpid_for(agent_id, proc.pid)

        with self._platform_lock:
            # version only: agents pull the snapshot when they grow a
            # policy/labeler consumer for it (reference pushes full
            # platform data because its agents label packets with it)
            resp.platform_version = self._platform_version
        return resp

    def GpidSync(self, request: pb.GpidSyncRequest,
                 context) -> pb.GpidSyncResponse:
        return self.gpids.sync(request)

    MAX_PUSH_STREAMS = 48  # worker pool is sized to keep unary headroom

    def Push(self, request: pb.SyncRequest, context):
        """Server-streaming: config-change notifications (reference:
        trisolaris push on version bump, sync_push.go pushmanager).
        Yields a SyncResponse whenever the agent's group config changes;
        replays the current config on subscribe when the agent is behind."""
        group = request.agent_group or "default"
        q: "queue.Queue" = queue.Queue(maxsize=16)
        with self._push_lock:
            if len(self._push_subs) >= self.MAX_PUSH_STREAMS:
                # explicit status so agents back off instead of hammering
                context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                              "push stream capacity reached")
            self._push_subs.append((group, q))
        try:
            # catch-up: a reconnecting agent may have missed updates
            cfg, version = self.configs.get(group)
            if request.config_version != version:
                resp = pb.SyncResponse()
                resp.status = pb.SUCCESS
                resp.user_config_yaml = cfg
                resp.config_version = version
                resp.config_epoch = self.configs.epoch
                yield resp
            while context.is_active():
                try:
                    resp = q.get(timeout=1.0)
                except queue.Empty:
                    continue
                yield resp
        finally:
            with self._push_lock:
                try:
                    self._push_subs.remove((group, q))
                except ValueError:
                    pass

    def _on_config_update(self, group: str, yaml_bytes: bytes,
                          version: int) -> None:
        resp = pb.SyncResponse()
        resp.status = pb.SUCCESS
        resp.user_config_yaml = yaml_bytes
        resp.config_version = version
        resp.config_epoch = self.configs.epoch
        with self._push_lock:
            subs = list(self._push_subs)
        for sub_group, q in subs:
            if sub_group == group:
                try:
                    q.put_nowait(resp)
                except queue.Full:
                    # keep the NEWEST config: drop one stale entry and retry
                    try:
                        q.get_nowait()
                        q.put_nowait(resp)
                    except (queue.Empty, queue.Full):
                        pass

    def _ingest_platform(self, agent_id: int, p: pb.PlatformData) -> None:
        """Genesis upload -> platform snapshot + ingester tag table."""
        with self._platform_lock:
            prev = self._platforms.get(agent_id)
            if prev is None or prev.SerializeToString() != \
                    p.SerializeToString():
                self._platforms[agent_id] = pb.PlatformData()
                self._platforms[agent_id].CopyFrom(p)
                self._platform_version += 1
        self.platform_table.update(AgentInfo(
            agent_id=agent_id,
            host=p.hostname,
            pod_name=p.pod_name,
            pod_ns=p.pod_namespace,
            tpu_pod=p.tpu_pod_name,
            tpu_worker=int(p.tpu_worker_id or 0),
            slice_id=p.devices[0].slice_id if p.devices else 0,
        ))

    def _merged_platform_locked(self) -> pb.PlatformData:
        merged = pb.PlatformData()
        for p in self._platforms.values():
            merged.devices.extend(p.devices)
            merged.slice_count = max(merged.slice_count, p.slice_count)
        return merged

    # -- server lifecycle -----------------------------------------------------

    def start(self) -> "Controller":
        handlers = {
            "Sync": grpc.unary_unary_rpc_method_handler(
                self.Sync,
                request_deserializer=pb.SyncRequest.FromString,
                response_serializer=pb.SyncResponse.SerializeToString),
            "GpidSync": grpc.unary_unary_rpc_method_handler(
                self.GpidSync,
                request_deserializer=pb.GpidSyncRequest.FromString,
                response_serializer=pb.GpidSyncResponse.SerializeToString),
            "Push": grpc.unary_stream_rpc_method_handler(
                self.Push,
                request_deserializer=pb.SyncRequest.FromString,
                response_serializer=pb.SyncResponse.SerializeToString),
        }
        generic = grpc.method_handlers_generic_handler(
            "deepflow_tpu.Synchronizer", handlers)
        # each Push stream pins a worker for its lifetime: size the pool so
        # MAX_PUSH_STREAMS streams still leave unary-RPC headroom
        self._server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=self.MAX_PUSH_STREAMS + 16))
        self._server.add_generic_rpc_handlers((generic,))
        self.port = self._server.add_insecure_port(
            f"{self.host}:{self.port}")
        self._server.start()
        log.info("controller sync up on :%d", self.port)
        return self

    def stop(self) -> None:
        if self._server:
            self._server.stop(grace=0.5)
            self._server = None
