"""deepflow-tpu server: one process running ingester + querier + controller.

Reference analog: server/cmd/server/main.go:112-115 (one Go binary, three
logical services). Here: receiver (framed TCP :20033) -> per-type decoder
queues -> tag injection -> columnar store; querier HTTP (:20416); controller
gRPC (:20035).
"""

from deepflow_tpu.server.server import Server  # noqa: F401
