"""Resource recorder: snapshot-diff of watched resources into change
events with before/after attributes.

Reference analog: controller/recorder/ (32.8k LoC of cache+updaters
diffing cloud/genesis snapshots into MySQL meta tables and emitting
resource-change events). Embedded redesign: one attr-level diff engine
keyed by (resource_type, key); genesis watch streams feed it and the
diffs land in event.event with a json attrs payload — the "what changed
right before the regression?" forensics view (VERDICT r04 next #9).

Semantics: a resync/relist re-ADD of an identical object is a no-op
(diff engines don't re-announce known state); an attribute change —
whatever the watch event type claimed — emits a `<type>-modified` event
carrying {attr: {before, after}} for exactly the attrs that changed.
"""

from __future__ import annotations

import json
import logging
import threading
import time

log = logging.getLogger("df.recorder")


class ResourceRecorder:
    """Attr-diff engine over the live resource snapshot."""

    MAX_TRACKED = 200_000  # runaway-churn guard

    def __init__(self, sink=None) -> None:
        self.sink = sink  # sink(rows: list[dict]) -> None
        self._snap: dict[tuple, dict] = {}
        self._lock = threading.Lock()
        self.stats = {"added": 0, "modified": 0, "deleted": 0,
                      "suppressed": 0}

    def _emit(self, rtype: str, key: str, verb: str, attrs_payload: dict,
              description: str) -> None:
        self.stats[verb] += 1
        if self.sink is None:
            return
        try:
            self.sink([{
                "time": time.time_ns(),
                "event_type": f"{rtype}-{verb}",
                "resource_type": rtype,
                "resource_name": key,
                "description": description,
                "attrs": json.dumps(attrs_payload, sort_keys=True),
            }])
        except Exception:
            log.debug("recorder sink failed", exc_info=True)

    @staticmethod
    def _describe(attrs: dict) -> str:
        parts = []
        for k, v in sorted(attrs.items()):
            if isinstance(v, (list, tuple)):
                v = ",".join(str(x) for x in v)
            elif isinstance(v, dict):
                continue
            parts.append(f"{k}={v}")
        return " ".join(parts)

    def observe(self, rtype: str, key: str, attrs: dict | None,
                deleted: bool = False, emit: bool = True) -> None:
        """Feed one observed object state. attrs None == deleted."""
        skey = (rtype, key)
        with self._lock:
            old = self._snap.get(skey)
            if deleted or attrs is None:
                if old is None:
                    return  # deleting the unknown: nothing to report
                del self._snap[skey]
                if emit:
                    self._emit(rtype, key, "deleted", {"before": old},
                               self._describe(old))
                else:
                    self.stats["suppressed"] += 1
                return
            if len(self._snap) >= self.MAX_TRACKED and old is None:
                return
            self._snap[skey] = attrs
            if old is None:
                if emit:
                    self._emit(rtype, key, "added", {"after": attrs},
                               self._describe(attrs))
                else:
                    self.stats["suppressed"] += 1
            elif old != attrs:
                changed = {
                    k: {"before": old.get(k), "after": attrs.get(k)}
                    for k in set(old) | set(attrs)
                    if old.get(k) != attrs.get(k)}
                # modified events ALWAYS emit, emit flag notwithstanding:
                # emit=False marks resync/relist observations, and an
                # attr change discovered by a recovery relist (the watch
                # was down when it happened) is exactly the change the
                # forensics timeline must not lose
                self._emit(
                    rtype, key, "modified", {"changed": changed},
                    " ".join(f"{k}: {v['before']}->{v['after']}"
                             for k, v in sorted(changed.items())))

    def reconcile(self, rtype: str, live_keys: set, emit: bool = True
                  ) -> int:
        """A relist is authoritative: tracked objects of `rtype` missing
        from live_keys were deleted during a watch gap — emit their
        deleted events (with last-known attrs) and drop them."""
        dropped = 0
        with self._lock:
            for skey in [s for s in self._snap
                         if s[0] == rtype and s[1] not in live_keys]:
                old = self._snap.pop(skey)
                dropped += 1
                if emit:
                    self._emit(rtype, skey[1], "deleted", {"before": old},
                               self._describe(old))
        return dropped

    def snapshot_keys(self, rtype: str) -> list[str]:
        with self._lock:
            return [k for (t, k) in self._snap if t == rtype]
