"""PlatformInfoTable: agent_id -> platform/topology tags for ingest-time
universal tag injection.

Reference analog: server/libs/grpc/grpc_platformdata.go:147 — the ingester's
cache of controller platform data, queried per row to inject universal tags.
TPU-native: tags carry TPU pod topology (tpu_pod, worker, slice) alongside
host/pod identity.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field


@dataclass
class AgentInfo:
    agent_id: int
    host: str = ""
    host_id: int = 0
    pod_name: str = ""
    pod_ns: str = ""
    tpu_pod: str = ""
    tpu_worker: int = 0
    slice_id: int = 0
    org_id: int = 1   # multi-tenancy scope; 1 = default org

    def tags(self) -> dict:
        return {
            "org_id": self.org_id,
            "agent_id": self.agent_id,
            "host_id": self.host_id,
            "host": self.host,
            "pod_name": self.pod_name,
            "pod_ns": self.pod_ns,
            "tpu_pod": self.tpu_pod,
            "tpu_worker": self.tpu_worker,
            "slice_id": self.slice_id,
        }


_EMPTY = AgentInfo(agent_id=0)


class PlatformInfoTable:
    """Thread-safe agent registry; fed by the controller (or directly by
    agent hello frames in standalone mode)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._agents: dict[int, AgentInfo] = {}
        self._next_host_id = 1
        # NTP-measured per-agent skew vs the controller clock; kept apart
        # from AgentInfo so platform re-uploads don't clobber it
        self._clock_offsets: dict[int, int] = {}

    def update(self, info: AgentInfo) -> None:
        with self._lock:
            prev = self._agents.get(info.agent_id)
            if info.host_id == 0:
                info.host_id = (prev.host_id if prev
                                else self._alloc_host_id_locked())
            self._agents[info.agent_id] = info

    def _alloc_host_id_locked(self) -> int:
        hid = self._next_host_id
        self._next_host_id += 1
        return hid

    def query(self, agent_id: int) -> AgentInfo:
        with self._lock:
            return self._agents.get(agent_id, _EMPTY)

    def tags_for(self, agent_id: int) -> dict:
        info = self.query(agent_id)
        if info is _EMPTY:
            # unknown agents land in the default org (single-org setups
            # never configure orgs and must keep working unchanged)
            return {"agent_id": agent_id, "org_id": 1}
        return info.tags()

    def set_clock_offset(self, agent_id: int, offset_ns: int) -> None:
        with self._lock:
            self._clock_offsets[agent_id] = int(offset_ns)

    def offset_for(self, agent_id: int) -> int:
        """ns to ADD to this agent's absolute timestamps to land on the
        controller clock (decoders normalize at ingest; reference agents
        correct on-host via rpc/ntp.rs — same capability, ingest-side)."""
        with self._lock:
            return self._clock_offsets.get(agent_id, 0)


@dataclass
class PodInfo:
    """One K8s workload endpoint (genesis resource model entry)."""
    name: str
    namespace: str = ""
    node: str = ""
    workload: str = ""  # owning deployment/statefulset/daemonset name
    labels: dict = field(default_factory=dict)


class PodIpIndex:
    """IP -> pod resource map fed by K8s genesis; queried per flow row to
    tag BOTH sides of a connection (reference: genesis -> recorder ->
    grpc_platformdata IP lookups)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._by_ip: dict[str, PodInfo] = {}
        self.version = 0

    def upsert(self, ip: str, pod: PodInfo) -> None:
        if not ip:
            return
        with self._lock:
            self._by_ip[ip] = pod
            self.version += 1

    def remove_ip(self, ip: str) -> None:
        with self._lock:
            if self._by_ip.pop(ip, None) is not None:
                self.version += 1

    def retain_ips(self, ips: set) -> int:
        """Evict entries outside `ips` (relist reconciliation). Returns
        the number removed."""
        with self._lock:
            dead = [ip for ip in self._by_ip if ip not in ips]
            for ip in dead:
                del self._by_ip[ip]
            if dead:
                self.version += 1
            return len(dead)

    def lookup(self, ip: str) -> PodInfo | None:
        with self._lock:
            return self._by_ip.get(ip)

    def snapshot(self) -> dict:
        """Reference to the current mapping for batch POINT reads (.get)
        only — iteration over this dict races writers; use items_copy()
        to iterate. Writers always replace values, never mutate them."""
        return self._by_ip

    def items_copy(self) -> list:
        """Locked copy for safe iteration (PodMap serving etc.)."""
        with self._lock:
            return list(self._by_ip.items())

    def __len__(self) -> int:
        with self._lock:
            return len(self._by_ip)


@dataclass
class ServiceInfo:
    """One K8s Service (genesis resource model entry)."""
    name: str
    namespace: str = ""
    cluster_ip: str = ""
    svc_type: str = ""          # ClusterIP / NodePort / LoadBalancer
    ports: tuple = ()           # (port, ...) for catalog introspection


@dataclass
class NodeInfo:
    """One K8s Node: identity + the topology tags universal tagging needs
    (reference: controller/tagrecorder ch_az / ch_subnet catalogs are fed
    from node+cloud metadata; here the node object is the source)."""
    name: str
    az: str = ""                # topology.kubernetes.io/zone
    region: str = ""            # topology.kubernetes.io/region
    internal_ip: str = ""
    pod_cidrs: tuple = ()       # spec.podCIDRs


@dataclass(frozen=True)
class EndpointTags:
    """Resolution result for one IP — the per-side universal tag set
    injected into every flow/metric row (reference analog:
    server/libs/grpc/grpc_platformdata.go:292 QueryIPV4Infos -> Info)."""
    resource_type: str = ""     # pod | service | node | ''
    pod: str = ""
    pod_ns: str = ""
    workload: str = ""          # owning deployment/statefulset (pod_group)
    node: str = ""
    service: str = ""
    az: str = ""
    subnet: str = ""


_EMPTY_TAGS = EndpointTags()


def _cidr_key(cidr: str):
    """(net_int, prefix_len) for a v4 CIDR, or None."""
    import ipaddress
    try:
        net = ipaddress.ip_network(cidr, strict=False)
    except ValueError:
        return None
    if net.version != 4:
        return None
    return int(net.network_address), net.prefixlen


class ResourceIndex:
    """IP-keyed cluster resource model: ip -> EndpointTags covering pods,
    service ClusterIPs, nodes, and subnet attribution by longest-prefix
    match over node podCIDRs.

    Reference analog: the PlatformInfoTable IP queries
    (server/libs/grpc/grpc_platformdata.go:147,:292,:376) backed by the
    tagrecorder ch_* dictionaries (controller/tagrecorder/const.go:66).
    Epoch-versioned: every mutation bumps `version` so consumers (PodMap
    serving, caches) can detect staleness cheaply.
    """

    def __init__(self, pod_index: PodIpIndex | None = None) -> None:
        self.pod_index = pod_index if pod_index is not None else PodIpIndex()
        self._lock = threading.Lock()
        self._svc_by_cluster_ip: dict[str, ServiceInfo] = {}
        self._svc_by_key: dict[tuple, ServiceInfo] = {}   # (ns, name)
        self._eps_by_svc: dict[tuple, frozenset] = {}     # (ns,name)->pod ips
        self._svc_by_pod_ip: dict[str, tuple] = {}        # ip -> (ns, name)
        self._node_by_name: dict[str, NodeInfo] = {}
        self._node_by_ip: dict[str, NodeInfo] = {}
        # sorted longest-prefix-first [(net_int, prefixlen, cidr_str)]
        self._subnets: list[tuple] = []
        self.version = 0

    # -- services -------------------------------------------------------------

    def upsert_service(self, svc: ServiceInfo) -> None:
        key = (svc.namespace, svc.name)
        with self._lock:
            prev = self._svc_by_key.get(key)
            if prev is not None and prev.cluster_ip and \
                    prev.cluster_ip != svc.cluster_ip:
                self._svc_by_cluster_ip.pop(prev.cluster_ip, None)
            self._svc_by_key[key] = svc
            if svc.cluster_ip and svc.cluster_ip.lower() != "none":
                self._svc_by_cluster_ip[svc.cluster_ip] = svc
            self.version += 1

    def remove_service(self, namespace: str, name: str) -> None:
        key = (namespace, name)
        with self._lock:
            svc = self._svc_by_key.pop(key, None)
            if svc is not None and svc.cluster_ip:
                self._svc_by_cluster_ip.pop(svc.cluster_ip, None)
            if self._eps_by_svc.pop(key, None):
                self._rebuild_pod_ip_map_locked()
            self.version += 1

    def retain_services(self, keys: set) -> None:
        with self._lock:
            dead = [k for k in self._svc_by_key if k not in keys]
            for k in dead:
                svc = self._svc_by_key.pop(k)
                if svc.cluster_ip:
                    self._svc_by_cluster_ip.pop(svc.cluster_ip, None)
            if dead:
                self.version += 1

    # -- endpoints ------------------------------------------------------------

    def set_endpoints(self, namespace: str, name: str, pod_ips) -> None:
        """Replace the backing-pod IP set for one service."""
        key = (namespace, name)
        ips = frozenset(pod_ips)
        with self._lock:
            if self._eps_by_svc.get(key) == ips:
                return
            if ips:
                self._eps_by_svc[key] = ips
            else:
                self._eps_by_svc.pop(key, None)
            self._rebuild_pod_ip_map_locked()
            self.version += 1

    def retain_endpoints(self, keys: set) -> None:
        with self._lock:
            dead = [k for k in self._eps_by_svc if k not in keys]
            for k in dead:
                del self._eps_by_svc[k]
            if dead:
                self._rebuild_pod_ip_map_locked()
                self.version += 1

    def _rebuild_pod_ip_map_locked(self) -> None:
        m: dict[str, tuple] = {}
        for key, ips in self._eps_by_svc.items():
            for ip in ips:
                m[ip] = key
        self._svc_by_pod_ip = m

    # -- nodes ----------------------------------------------------------------

    def upsert_node(self, node: NodeInfo) -> None:
        with self._lock:
            prev = self._node_by_name.get(node.name)
            if prev is not None and prev.internal_ip and \
                    prev.internal_ip != node.internal_ip:
                self._node_by_ip.pop(prev.internal_ip, None)
            self._node_by_name[node.name] = node
            if node.internal_ip:
                self._node_by_ip[node.internal_ip] = node
            self._rebuild_subnets_locked()
            self.version += 1

    def remove_node(self, name: str) -> None:
        with self._lock:
            node = self._node_by_name.pop(name, None)
            if node is not None:
                if node.internal_ip:
                    self._node_by_ip.pop(node.internal_ip, None)
                self._rebuild_subnets_locked()
            self.version += 1

    def retain_nodes(self, names: set) -> None:
        with self._lock:
            dead = [n for n in self._node_by_name if n not in names]
            for n in dead:
                node = self._node_by_name.pop(n)
                if node.internal_ip:
                    self._node_by_ip.pop(node.internal_ip, None)
            if dead:
                self._rebuild_subnets_locked()
                self.version += 1

    def _rebuild_subnets_locked(self) -> None:
        subnets = []
        for node in self._node_by_name.values():
            for cidr in node.pod_cidrs:
                key = _cidr_key(cidr)
                if key is not None:
                    subnets.append((key[0], key[1], cidr))
        subnets.sort(key=lambda t: -t[1])   # longest prefix first
        self._subnets = subnets

    def _subnet_of_locked(self, ip: str) -> str:
        if not self._subnets or "." not in ip:
            return ""
        try:
            parts = ip.split(".")
            ip_int = (int(parts[0]) << 24) | (int(parts[1]) << 16) | \
                     (int(parts[2]) << 8) | int(parts[3])
        except (ValueError, IndexError):
            return ""
        for net, plen, cidr in self._subnets:
            if (ip_int >> (32 - plen)) << (32 - plen) == net:
                return cidr
        return ""

    # -- resolution -----------------------------------------------------------

    def resolve(self, ip: str) -> EndpointTags:
        pod = self.pod_index.lookup(ip)
        with self._lock:
            subnet = self._subnet_of_locked(ip)
            if pod is not None:
                svc_key = self._svc_by_pod_ip.get(ip)
                node = self._node_by_name.get(pod.node)
                return EndpointTags(
                    resource_type="pod", pod=pod.name, pod_ns=pod.namespace,
                    workload=pod.workload, node=pod.node,
                    service=svc_key[1] if svc_key else "",
                    az=node.az if node else "", subnet=subnet)
            svc = self._svc_by_cluster_ip.get(ip)
            if svc is not None:
                return EndpointTags(resource_type="service",
                                    pod_ns=svc.namespace, service=svc.name,
                                    subnet=subnet)
            node = self._node_by_ip.get(ip)
            if node is not None:
                return EndpointTags(resource_type="node", node=node.name,
                                    az=node.az, subnet=subnet)
            return EndpointTags(subnet=subnet) if subnet else _EMPTY_TAGS

    def is_empty(self) -> bool:
        """True when no resource can resolve to anything — decoders then
        skip per-row resolution entirely (the common standalone case)."""
        with self._lock:
            has_any = (self._svc_by_key or self._node_by_name
                       or self._subnets)
        return not has_any and len(self.pod_index) == 0

    def batch_resolver(self):
        """Per-batch memoized resolve: decoders call this once per batch so
        repeated IPs cost one dict hit, not a lock round-trip."""
        cache: dict[str, EndpointTags] = {}

        def resolve(ip: str) -> EndpointTags:
            t = cache.get(ip)
            if t is None:
                t = self.resolve(ip)
                cache[ip] = t
            return t
        return resolve

    # -- introspection (catalog / dfctl) --------------------------------------

    def summary(self) -> dict:
        with self._lock:
            return {
                "pods": len(self.pod_index),
                "services": len(self._svc_by_key),
                "endpoints": len(self._eps_by_svc),
                "nodes": len(self._node_by_name),
                "subnets": len(self._subnets),
                "version": self.version + self.pod_index.version,
            }

    def services_copy(self) -> list:
        with self._lock:
            return list(self._svc_by_key.values())

    def nodes_copy(self) -> list:
        with self._lock:
            return list(self._node_by_name.values())
