"""PlatformInfoTable: agent_id -> platform/topology tags for ingest-time
universal tag injection.

Reference analog: server/libs/grpc/grpc_platformdata.go:147 — the ingester's
cache of controller platform data, queried per row to inject universal tags.
TPU-native: tags carry TPU pod topology (tpu_pod, worker, slice) alongside
host/pod identity.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field


@dataclass
class AgentInfo:
    agent_id: int
    host: str = ""
    host_id: int = 0
    pod_name: str = ""
    pod_ns: str = ""
    tpu_pod: str = ""
    tpu_worker: int = 0
    slice_id: int = 0

    def tags(self) -> dict:
        return {
            "agent_id": self.agent_id,
            "host_id": self.host_id,
            "host": self.host,
            "pod_name": self.pod_name,
            "pod_ns": self.pod_ns,
            "tpu_pod": self.tpu_pod,
            "tpu_worker": self.tpu_worker,
            "slice_id": self.slice_id,
        }


_EMPTY = AgentInfo(agent_id=0)


class PlatformInfoTable:
    """Thread-safe agent registry; fed by the controller (or directly by
    agent hello frames in standalone mode)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._agents: dict[int, AgentInfo] = {}
        self._next_host_id = 1
        # NTP-measured per-agent skew vs the controller clock; kept apart
        # from AgentInfo so platform re-uploads don't clobber it
        self._clock_offsets: dict[int, int] = {}

    def update(self, info: AgentInfo) -> None:
        with self._lock:
            prev = self._agents.get(info.agent_id)
            if info.host_id == 0:
                info.host_id = (prev.host_id if prev
                                else self._alloc_host_id_locked())
            self._agents[info.agent_id] = info

    def _alloc_host_id_locked(self) -> int:
        hid = self._next_host_id
        self._next_host_id += 1
        return hid

    def query(self, agent_id: int) -> AgentInfo:
        with self._lock:
            return self._agents.get(agent_id, _EMPTY)

    def tags_for(self, agent_id: int) -> dict:
        info = self.query(agent_id)
        if info is _EMPTY:
            return {"agent_id": agent_id}
        return info.tags()

    def set_clock_offset(self, agent_id: int, offset_ns: int) -> None:
        with self._lock:
            self._clock_offsets[agent_id] = int(offset_ns)

    def offset_for(self, agent_id: int) -> int:
        """ns to ADD to this agent's absolute timestamps to land on the
        controller clock (decoders normalize at ingest; reference agents
        correct on-host via rpc/ntp.rs — same capability, ingest-side)."""
        with self._lock:
            return self._clock_offsets.get(agent_id, 0)


@dataclass
class PodInfo:
    """One K8s workload endpoint (genesis resource model entry)."""
    name: str
    namespace: str = ""
    node: str = ""
    workload: str = ""  # owning deployment/statefulset/daemonset name
    labels: dict = field(default_factory=dict)


class PodIpIndex:
    """IP -> pod resource map fed by K8s genesis; queried per flow row to
    tag BOTH sides of a connection (reference: genesis -> recorder ->
    grpc_platformdata IP lookups)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._by_ip: dict[str, PodInfo] = {}
        self.version = 0

    def upsert(self, ip: str, pod: PodInfo) -> None:
        if not ip:
            return
        with self._lock:
            self._by_ip[ip] = pod
            self.version += 1

    def remove_ip(self, ip: str) -> None:
        with self._lock:
            if self._by_ip.pop(ip, None) is not None:
                self.version += 1

    def retain_ips(self, ips: set) -> int:
        """Evict entries outside `ips` (relist reconciliation). Returns
        the number removed."""
        with self._lock:
            dead = [ip for ip in self._by_ip if ip not in ips]
            for ip in dead:
                del self._by_ip[ip]
            if dead:
                self.version += 1
            return len(dead)

    def lookup(self, ip: str) -> PodInfo | None:
        with self._lock:
            return self._by_ip.get(ip)

    def snapshot(self) -> dict:
        """Reference to the current mapping for batch POINT reads (.get)
        only — iteration over this dict races writers; use items_copy()
        to iterate. Writers always replace values, never mutate them."""
        return self._by_ip

    def items_copy(self) -> list:
        """Locked copy for safe iteration (PodMap serving etc.)."""
        with self._lock:
            return list(self._by_ip.items())

    def __len__(self) -> int:
        with self._lock:
            return len(self._by_ip)
