"""Step health: host-partial merge, rolling baselines, critical-path
attribution, and the streaming EWMA+MAD regression scorer.

One record in profile.tpu_step_metrics is a single HOST's view of one
(job, run_id, step) — the agent only sees its local devices. Everything
here reconstructs pod-level truth from those partials with EXACT merges
(min/max/sum), which is also what makes the cluster-federated path exact:
the coordinator unions host rows across shards (each host's record lands
on exactly one shard) and runs the same merge.

Shared by the querier's /v1/tpu/steps endpoints, the alerting
StepRegressionDetector, and cli/steps_check.py — one implementation, so
the alert's verdict and the query API's verdict can never disagree.
"""

from __future__ import annotations

import json
from collections import deque
from statistics import median

BASELINE_LEN = 32        # healthy steps kept per job for attribution
EWMA_ALPHA = 0.3
MAD_K = 4.0              # fire past ewma + K * 1.4826 * MAD
MIN_STEPS = 5            # warmup before anything may fire
MAD_WINDOW = 64          # residuals kept for the MAD estimate
# relative floor on the threshold: sub-noise corpora (near-zero MAD after
# identical synthetic steps) must not fire on a 1ns wobble
REL_FLOOR = 0.05


def _top_hlos(val) -> list:
    """Rows carry top_hlos as a json string; agent records as a list."""
    if isinstance(val, str):
        try:
            val = json.loads(val) if val else []
        except json.JSONDecodeError:
            val = []
    return [list(h) for h in (val or []) if len(h) >= 2]


def merge_host_partials(rows: list[dict]) -> list[dict]:
    """Fold per-host tpu_step_metrics rows into one rollup per
    (job, run_id, step), time-ordered. Exact merges only: start=min,
    end=max, totals=sum; the cross-host device-end spread comes from each
    host's (end_ns, device_skew_ns) pair — end_ns - device_skew_ns is
    that host's EARLIEST device end, so the global spread needs no
    per-device data."""
    by_key: dict[tuple, dict] = {}
    for r in rows:
        key = (str(r.get("job") or ""), int(r.get("run_id") or 0),
               int(r.get("step") or 0))
        t0 = int(r.get("time") or 0)
        t1 = int(r.get("end_ns") or 0)
        first_end = t1 - int(r.get("device_skew_ns") or 0)
        cur = by_key.get(key)
        if cur is None:
            by_key[key] = cur = {
                "job": key[0], "run_id": key[1], "step": key[2],
                "time": t0, "end_ns": t1, "_first_end": first_end,
                "device_count": 0, "compute_ns": 0, "collective_ns": 0,
                "straggler_device": int(r.get("straggler_device") or 0),
                "straggler_host": str(r.get("host") or ""),
                "straggler_lag_ns": int(r.get("straggler_lag_ns") or 0),
                "hosts": [], "_hlos": {}, "records": 0,
            }
        else:
            cur["time"] = min(cur["time"], t0)
            cur["_first_end"] = min(cur["_first_end"], first_end)
            if t1 > cur["end_ns"]:
                cur["end_ns"] = t1
                # the straggler is wherever the LATEST device end lives
                cur["straggler_device"] = int(
                    r.get("straggler_device") or 0)
                cur["straggler_host"] = str(r.get("host") or "")
                cur["straggler_lag_ns"] = int(
                    r.get("straggler_lag_ns") or 0)
        cur["device_count"] += int(r.get("device_count") or 0)
        cur["compute_ns"] += int(r.get("compute_ns") or 0)
        cur["collective_ns"] += int(r.get("collective_ns") or 0)
        cur["records"] += 1
        host = str(r.get("host") or "")
        if host and host not in cur["hosts"]:
            cur["hosts"].append(host)
        for op, self_ns, *rest in _top_hlos(r.get("top_hlos")):
            cat = rest[0] if rest else ""
            h = cur["_hlos"].get(op)
            if h is None:
                cur["_hlos"][op] = [int(self_ns), cat]
            else:
                h[0] += int(self_ns)
    out = []
    for cur in by_key.values():
        cur["latency_ns"] = max(0, cur["end_ns"] - cur["time"])
        cur["device_skew_ns"] = max(
            0, cur["end_ns"] - cur.pop("_first_end"))
        hlos = sorted(cur.pop("_hlos").items(), key=lambda kv: -kv[1][0])
        cur["top_hlos"] = [[op, h[0], h[1]] for op, h in hlos]
        cur["hosts"].sort()
        out.append(cur)
    out.sort(key=lambda c: (c["time"], c["run_id"], c["step"]))
    return out


def baseline_of(rollups: list[dict]) -> dict | None:
    """Medians of recent HEALTHY steps: the 'what normal looks like' this
    step gets diffed against. None until there is at least one."""
    if not rollups:
        return None
    per_op: dict[str, list[int]] = {}
    for r in rollups:
        for op, self_ns, *_ in r.get("top_hlos", []):
            per_op.setdefault(op, []).append(int(self_ns))
    return {
        "n_steps": len(rollups),
        "latency_ns": int(median(r["latency_ns"] for r in rollups)),
        "compute_ns": int(median(r["compute_ns"] for r in rollups)),
        "collective_ns": int(median(r["collective_ns"] for r in rollups)),
        "device_skew_ns": int(
            median(r["device_skew_ns"] for r in rollups)),
        "hlo_ns": {op: int(median(v)) for op, v in per_op.items()},
    }


def attribute(step: dict, baseline: dict | None) -> dict:
    """Critical-path attribution: where did this step's latency go,
    relative to the baseline — per-device compute, collective wait, or
    device skew (straggler)? Components are normalized per device so a
    host joining/leaving between baseline and step doesn't masquerade as
    a compute regression."""
    ndev = max(1, int(step.get("device_count") or 1))
    comp = {
        "compute": step["compute_ns"] // ndev,
        "collective": step["collective_ns"] // ndev,
        "skew": step["device_skew_ns"],
    }
    if baseline:
        # baseline totals are medians of merged (all-device) sums, so the
        # same per-device normalization applies
        base = {
            "compute": baseline["compute_ns"] // ndev,
            "collective": baseline["collective_ns"] // ndev,
            "skew": baseline["device_skew_ns"],
        }
    else:
        base = {k: 0 for k in comp}
    deltas = {k: comp[k] - base[k] for k in comp}
    verdict = max(deltas, key=lambda k: deltas[k])
    base_hlos = (baseline or {}).get("hlo_ns", {})
    dom = []
    for op, self_ns, *rest in step.get("top_hlos", []):
        b = int(base_hlos.get(op, 0))
        dom.append({"hlo_op": op, "self_ns": int(self_ns),
                    "baseline_ns": b, "delta_ns": int(self_ns) - b,
                    "category": rest[0] if rest else ""})
    dom.sort(key=lambda d: -d["delta_ns"])
    return {
        "verdict": verdict,
        "latency_ns": step["latency_ns"],
        "baseline_latency_ns": (baseline or {}).get("latency_ns", 0),
        "delta_ns": step["latency_ns"]
        - (baseline or {}).get("latency_ns", 0),
        "components_ns": comp,
        "baseline_components_ns": base,
        "component_deltas_ns": deltas,
        "straggler_device": step.get("straggler_device", 0),
        "straggler_host": step.get("straggler_host", ""),
        "straggler_lag_ns": step.get("straggler_lag_ns", 0),
        "dominant_hlos": dom[:5],
        "baseline_steps": (baseline or {}).get("n_steps", 0),
    }


class EwmaMad:
    """Streaming EWMA mean + MAD spread over step latency for ONE job.

    feed() returns True when the step is a regression: warmup done AND
    latency > ewma + K * 1.4826 * MAD, with a relative floor so
    noise-free corpora don't fire on jitter. Regressed steps do NOT
    update the mean/spread/baseline — a slow plateau must keep firing
    against the healthy past, not get absorbed into it."""

    def __init__(self, alpha: float = EWMA_ALPHA, k: float = MAD_K,
                 min_steps: int = MIN_STEPS,
                 baseline_len: int = BASELINE_LEN) -> None:
        self.alpha = alpha
        self.k = k
        self.min_steps = min_steps
        self.ewma: float | None = None
        self.n = 0
        self.residuals: deque[float] = deque(maxlen=MAD_WINDOW)
        self.healthy: deque[dict] = deque(maxlen=baseline_len)
        self.last_threshold_ns = 0.0

    def threshold_ns(self) -> float:
        if self.ewma is None:
            return float("inf")
        mad = median(self.residuals) if self.residuals else 0.0
        return self.ewma + max(self.k * 1.4826 * mad,
                               REL_FLOOR * self.ewma)

    def feed(self, rollup: dict) -> bool:
        lat = float(rollup["latency_ns"])
        if self.ewma is None:
            self.ewma = lat
            self.n = 1
            self.healthy.append(rollup)
            self.last_threshold_ns = self.threshold_ns()
            return False
        thr = self.threshold_ns()
        self.last_threshold_ns = thr
        if self.n >= self.min_steps and lat > thr:
            return True
        self.residuals.append(abs(lat - self.ewma))
        self.ewma += self.alpha * (lat - self.ewma)
        self.n += 1
        self.healthy.append(rollup)
        return False

    def baseline(self) -> dict | None:
        return baseline_of(list(self.healthy))


def score_timeline(rollups: list[dict], alpha: float = EWMA_ALPHA,
                   k: float = MAD_K,
                   min_steps: int = MIN_STEPS) -> list[dict]:
    """Batch replay of the streaming detector over a merged timeline:
    annotates each rollup with regressed/threshold/verdict in place-order.
    This is the exact logic the StepRegressionDetector runs live, so the
    timeline a human reads agrees with the alerts that fired."""
    scorers: dict[str, EwmaMad] = {}
    out = []
    for r in rollups:
        sc = scorers.get(r["job"])
        if sc is None:
            scorers[r["job"]] = sc = EwmaMad(
                alpha=alpha, k=k, min_steps=min_steps)
        baseline = sc.baseline()
        regressed = sc.feed(r)
        ann = dict(r)
        ann["regressed"] = regressed
        ann["threshold_ns"] = int(sc.last_threshold_ns) \
            if sc.last_threshold_ns != float("inf") else 0
        att = attribute(r, baseline)
        ann["verdict"] = att["verdict"] if regressed else "ok"
        if regressed:
            ann["attribution"] = att
        out.append(ann)
    return out
